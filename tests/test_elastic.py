"""ffelastic tests (elastic/, docs/elastic.md).

The acceptance surface of the drift/capacity-triggered live re-planning
controller:

  - a sustained synthetic drift excursion produces EXACTLY ONE re-plan
    (the monitor's hysteresis is the single trigger source — the
    manager's own recompile hook is disarmed while a controller is
    attached), the recompile lands plan_source "replan" with the
    underlying origin preserved, and the decision record carries both
    sides of the payoff inequality;
  - the payoff rule declines a too-expensive move: the decision is
    recorded but the running plan (executor object included) survives
    bit-identically and training continues;
  - a capacity SHRINK (devices vanish from under the compiled mesh)
    forces a re-plan onto the smaller mesh whose continued trajectory is
    bit-exact vs a checkpoint-restart of the same state at the same
    target;
  - --elastic-dry-run runs trigger → search → gate → price and records
    the decision, but never migrates;
  - a serving-engine decode-mesh re-plan preserves the in-flight slot
    token streams exactly;
  - the migration-fidelity ratio measured by migrate_state feeds the
    payoff EMA and round-trips the warm-start calibration DB.
"""

import json
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.quick

DP4 = (4, 1, 1, 1)
DP2 = (2, 1, 1, 1)


def _mlp(batch=8, mesh=DP4, seed=0, argv=()):
    sys.argv = ["test", *argv]
    from flexflow_tpu import (
        ActiMode, FFConfig, FFModel, LossType, SGDOptimizer,
    )

    config = FFConfig()
    if config.mesh_axis_sizes is None:
        config.mesh_axis_sizes = mesh
    config.batch_size = batch
    config.seed = seed
    ff = FFModel(config)
    x = ff.create_tensor((batch, 16), name="x")
    t = ff.dense(x, 32, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 4, name="fc2")
    t = ff.softmax(t, name="sm")
    ff.compile(optimizer=SGDOptimizer(lr=0.05, momentum=0.9),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff


def _data(n=16, d=16, k=4, seed=0):
    rs = np.random.RandomState(seed)
    x = {"x": rs.randn(n, d).astype(np.float32)}
    y = rs.randint(0, k, (n, 1)).astype(np.int32)
    return x, y


def _fit(ff, epochs=1, seed=0):
    x, y = _data(seed=seed)
    ff.fit(x, y, epochs=epochs, batch_size=8, shuffle=False,
           verbose=False)
    return ff


def _flat(tree):
    import jax.tree_util as jtu

    return {jtu.keystr(p): np.asarray(v)
            for p, v in jtu.tree_flatten_with_path(tree)[0]}


# ======================================================== drift trigger


def test_sustained_drift_triggers_exactly_one_replan(tmp_path):
    """One sustained excursion, one re-plan: the advisory's hysteresis
    is the single trigger source, cooldown swallows the tail, and the
    recompile is a first-class plan_source "replan" whose decision
    record reproduces from the report alone."""
    ff = _mlp(argv=["--telemetry-dir", str(tmp_path / "t"),
                    "--diagnostics", "--budget", "20"])
    _fit(ff)
    diag = ff.get_diagnostics()
    import jax

    # pin the visible set to the compiled mesh so ONLY drift can trigger
    ctrl = ff.enable_elastic(cooldown_steps=4, horizon_steps=10_000,
                             visible_devices_fn=lambda: jax.devices()[:4])
    # satellite dedupe: attaching the controller disarms the monitor's
    # own recompile hook — the advisory stream has ONE consumer
    assert diag.elastic is ctrl
    assert diag.drift.recompile_state is None

    pred = ff._predicted_step_s
    step0 = ff._py_step()
    old_executor = ff.executor
    for i in range(1, 11):  # advisory fires once warmup (5 samples) clears
        step = step0 + i
        # one excursion: 10x the prediction until the re-plan lands,
        # back to the (refreshed) prediction after — hysteresis plus
        # cooldown must yield exactly one decision, not one per step
        dev = (ff._predicted_step_s if ctrl.decisions else pred * 10)
        diag.on_step({"step": step, "loss": 0.1,
                      "step_time_s": dev, "device_time_s": dev})
        ctrl.maybe_replan(step)

    assert len(ctrl.decisions) == 1, ctrl.decisions
    dec = ctrl.decisions[0]
    assert dec["trigger"] == "drift"
    assert dec["decision"] == "migrated"
    # both sides of the inequality are in the record, and they
    # reproduce from their factors (the run_doctor --check identity)
    lhs = dec["predicted_migration_s"] * dec["fidelity_ratio"]
    rhs = dec["benefit_s_per_step"] * dec["horizon_steps"]
    assert dec["lhs_s"] == pytest.approx(lhs)
    assert dec["rhs_s"] == pytest.approx(rhs)
    assert lhs < rhs
    assert dec["advisory"]["rule"] == "costmodel_drift"
    # the recompile is relabeled: replan, origin preserved
    assert ff._plan_source == "replan"
    assert ff._plan_origin in ("search", "cache")
    # migration happened → the executor was rebuilt
    assert ff.executor is not old_executor

    # the strategy report's elastic section carries the decision
    rep = json.load(open(tmp_path / "t" / "strategy_report.json"))
    assert rep["plan_source"] == "replan"
    assert rep["elastic"]["migrations"] == 1
    rdec = rep["elastic"]["decisions"][0]
    assert rdec["lhs_s"] == pytest.approx(dec["lhs_s"])
    assert rdec["rhs_s"] == pytest.approx(dec["rhs_s"])

    # training continues on the re-planned model
    _fit(ff)


# ========================================================= payoff gate


def test_payoff_declines_unprofitable_move():
    """A move that buys nothing (no measured excursion above the new
    plan's prediction) fails the payoff inequality; the decision is
    recorded with both sides, nothing migrates, and the running plan
    survives object-identically."""
    from flexflow_tpu.elastic import replan

    ff = _fit(_mlp())
    ff._migration_fidelity = (1e12, 3)  # as if calibrated: moves are ruinous
    old_executor = ff.executor
    before = _flat(ff._params)

    dec = replan(ff, step=ff._py_step(), trigger="capacity",
                 horizon_steps=1000, new_mesh_axes=(2, 2, 1, 1),
                 measured_ema_s=None)
    assert dec["decision"] == "declined"
    assert dec["would_migrate"] is False
    assert not dec["lhs_s"] < dec["rhs_s"]  # the rule, verbatim
    assert dec["fidelity_ratio"] == pytest.approx(1e12)
    assert ff._elastic_decisions[-1] is dec
    # rollback is invisible: same executor object, same mesh, same bits
    assert ff.executor is old_executor
    assert dict(ff.mesh.shape)["data"] == 4
    after = _flat(ff._params)
    assert before.keys() == after.keys()
    for k in before:
        assert np.array_equal(before[k], after[k]), k
    _fit(ff)  # and training still runs on the restored plan


def test_dry_run_decides_but_never_migrates():
    """--elastic-dry-run: the full trigger → search → gate → price
    pipeline runs and records what it WOULD do; the model is untouched."""
    from flexflow_tpu.elastic import replan

    ff = _fit(_mlp())
    old_executor = ff.executor
    old_source = ff._plan_source
    dec = replan(ff, step=ff._py_step(), trigger="drift",
                 horizon_steps=10_000, dry_run=True,
                 measured_ema_s=(ff._predicted_step_s or 1e-3) * 10)
    assert dec["decision"] == "dry_run"
    assert dec["would_migrate"] is True  # it WOULD have moved
    assert ff.executor is old_executor
    assert ff._plan_source == old_source  # restore wound back the label
    _fit(ff)


# ==================================================== capacity trigger


def test_capacity_shrink_bit_exact_vs_checkpoint_restart(tmp_path):
    """Devices vanish (4 → 2 visible): the controller force-replans onto
    the smaller mesh mid-run, and the continued trajectory is bit-exact
    vs checkpointing at the same point and restarting at the same
    target mesh."""
    import jax

    ff = _fit(_mlp())
    ff.save_checkpoint(str(tmp_path / "ck"))

    ctrl = ff.enable_elastic(
        cooldown_steps=0, horizon_steps=1000,
        visible_devices_fn=lambda: jax.devices()[:2],
        capacity_check_every=1)
    _fit(ff, seed=1)  # fit-entry capacity check replans before step 1

    assert len(ctrl.decisions) == 1, ctrl.decisions
    dec = ctrl.decisions[0]
    assert dec["trigger"] == "capacity"
    assert dec["decision"] == "migrated"
    assert dec["forced"] is True  # shrink migrates regardless of payoff
    assert dec["capacity"]["shrink"] is True
    assert dict(ff.mesh.shape)["data"] == 2
    # the inequality was still recorded for the audit trail
    assert "lhs_s" in dec and "rhs_s" in dec
    # satellite: the real (priced) migration fed its measured/predicted
    # ratio into the fidelity EMA — first sample replaces the default
    if dec["predicted_migration_s"] > 0:
        assert getattr(ff, "_migration_fidelity", None) is not None
        assert ff._migration_fidelity[1] == 1

    # control: checkpoint-restart at the same target mesh, same data
    ctrl_ff = _mlp(mesh=DP2)
    ctrl_ff.load_checkpoint(str(tmp_path / "ck"))
    _fit(ctrl_ff, seed=1)

    fa, fb = _flat(ff._params), _flat(ctrl_ff._params)
    assert fa.keys() == fb.keys()
    for k in fa:
        assert np.array_equal(fa[k], fb[k]), k
    sa, sb = _flat(ff._opt_slots), _flat(ctrl_ff._opt_slots)
    for k in sa:
        assert np.array_equal(sa[k], sb[k]), k
    assert int(ff._step) == int(ctrl_ff._step)


def test_capacity_undividable_declines_without_search():
    """A visible count the fixed axes cannot divide is declined with a
    recorded decision — no search, no compile, no mesh change."""
    import jax

    ff = _fit(_mlp(mesh=(2, 2, 1, 1)))
    ctrl = ff.enable_elastic(
        cooldown_steps=0,
        visible_devices_fn=lambda: jax.devices()[:3],  # 3 % (model=2) != 0
        capacity_check_every=1)
    old_executor = ff.executor
    assert ctrl.maybe_replan(ff._py_step()) is False
    dec = ctrl.decisions[-1]
    assert dec["decision"] == "declined"
    assert dec["capacity"]["new_axes"] is None
    assert "lhs_s" not in dec  # no search ran — nothing was priced
    assert ff.executor is old_executor


# ============================================================= serving


def test_serving_replan_preserves_inflight_token_streams():
    """A decode-mesh re-plan between scheduler iterations: requests
    mid-decode keep their KV state (migrated, verified) and finish with
    exactly the tokens an undisturbed engine produces."""
    sys.argv = ["test"]
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import (
        TransformerLMConfig, build_transformer_lm,
    )

    def build():
        cfg = FFConfig()
        cfg.mesh_axis_sizes = (1, 1, 1, 1)
        cfg.batch_size = 1
        ff = FFModel(cfg)
        build_transformer_lm(ff, TransformerLMConfig(
            vocab_size=64, hidden_size=32, num_heads=4, num_layers=2,
            sequence_length=32, attention_impl="xla"), batch_size=1)
        ff.compile(optimizer=SGDOptimizer(lr=0.01),
                   loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
        return ff

    prompts = [[3, 7, 11, 2, 5], [60, 1, 2]]
    ff = build()
    want = ff.serve(slots=2, max_new_tokens=8,
                    prefill_chunk=4).generate(prompts)

    eng = ff.serve(slots=2, max_new_tokens=8, prefill_chunk=4)
    reqs = [eng.submit(p) for p in prompts]
    for _ in range(4):  # prefill + a few decoded tokens in flight
        eng.step()
    assert any(not r.finished for r in reqs)
    mid = [list(r.generated) for r in reqs]

    dec = eng.replan_mesh((2, 1, 1, 1), trigger="capacity")
    assert dec["decision"] == "migrated"
    assert dict(eng.decode_model.mesh.shape)["data"] == 2
    assert eng.replan_decisions[-1] is dec

    for _ in range(64):
        if all(r.finished for r in reqs):
            break
        eng.step()
    got = [list(r.generated) for r in reqs]
    assert got == want
    # the pre-replan prefix really was generated before the move
    for g, m in zip(got, mid):
        assert g[:len(m)] == m


# ============================================== fidelity calibration DB


def test_migration_fidelity_ema_and_db_roundtrip(tmp_path):
    """record_fidelity: first sample replaces the default, later samples
    EMA-fold, and the ratio persists in the warm-start calibration DB
    under the reserved per-device-kind key so a NEW process starts from
    the calibrated value instead of the bench default."""
    from flexflow_tpu.elastic.payoff import (
        load_fidelity, record_fidelity,
    )

    wdir = str(tmp_path / "warm")
    ff = _mlp(argv=["--warmstart-dir", wdir])
    assert load_fidelity(ff) == (1.0, 0)
    assert record_fidelity(ff, 40.0) == (40.0, 1)
    r, n = record_fidelity(ff, 20.0)  # EMA alpha 0.5
    assert n == 2 and r == pytest.approx(30.0)

    ff2 = _mlp(argv=["--warmstart-dir", wdir])  # fresh model, same DB
    r2, n2 = load_fidelity(ff2)
    assert (r2, n2) == (pytest.approx(30.0), 2)

    ff3 = _mlp()  # no DB anywhere → the default
    assert load_fidelity(ff3) == (1.0, 0)
