"""Pipelined execution engine tests (engine/): fused multi-step dispatch,
async input prefetch, chunk-boundary resilience, deferred health sync.

The headline property: `fit(..., pipeline_steps=N)` is BIT-IDENTICAL to
the eager loop — same losses, params, RNG stream, and step counters over
multiple shuffled epochs — while dispatching the epoch in ceil(B/N) fused
scans instead of B per-step calls, and resuming across kills to the same
trajectory.
"""

import os
import sys
import threading

import numpy as np
import pytest

pytestmark = pytest.mark.quick

DP8 = (8, 1, 1, 1)


def _mlp(batch=8, mesh=DP8, seed=0, argv=()):
    sys.argv = ["test", *argv]
    from flexflow_tpu import (
        ActiMode, FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
    )

    config = FFConfig()
    config.mesh_axis_sizes = mesh
    config.batch_size = batch
    config.seed = seed
    ff = FFModel(config)
    x = ff.create_tensor((batch, 16), name="x")
    t = ff.dense(x, 32, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 4, name="fc2")
    t = ff.softmax(t, name="sm")
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])
    return ff


def _data(n=64, d=16, k=4, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, d).astype(np.float32)
    y = rs.randint(0, k, (n, 1)).astype(np.int32)
    return x, y


class _StepSpy:
    """Diagnostics rule that records every per-step record it sees —
    the loss stream both loops feed the health engine."""

    name = "step_spy"

    def __init__(self):
        self.records = []

    def check(self, rec):
        self.records.append((int(rec["step"]), rec.get("loss")))
        return None


def _weights(ff):
    import jax

    return {
        "fc1": np.asarray(jax.device_get(ff.get_weight("fc1", "kernel"))),
        "fc2": np.asarray(jax.device_get(ff.get_weight("fc2", "kernel"))),
    }


def _no_prefetch_threads():
    return not [t for t in threading.enumerate()
                if t.name.startswith("ff-prefetch") and t.is_alive()]


# ===================================================================
# chunk planning + chunk-aware checkpoint policy
# ===================================================================

def test_plan_chunks():
    from flexflow_tpu.engine import plan_chunks

    assert plan_chunks(0, 8, 4) == [(0, 4), (4, 4)]
    assert plan_chunks(0, 8, 3) == [(0, 3), (3, 3), (6, 2)]  # tail chunk
    assert plan_chunks(5, 8, 4) == [(5, 3)]  # resume mid-epoch
    assert plan_chunks(8, 8, 4) == []  # nothing left
    assert plan_chunks(0, 1, 64) == [(0, 1)]
    with pytest.raises(ValueError):
        plan_chunks(0, 8, 0)


def test_checkpoint_policy_should_save_range():
    from flexflow_tpu.resilience import CheckpointPolicy

    p = CheckpointPolicy(every_n_steps=3)
    # chunk 5..8 contains step 6 — must save even though 8 % 3 != 0
    assert p.should_save_range(4, 8)
    assert p.should_save_range(0, 4)  # contains 3
    assert not p.should_save_range(3, 5)  # 4, 5: no multiple of 3
    assert not p.should_save_range(4, 4)  # empty range
    assert not CheckpointPolicy().should_save_range(0, 100)  # policy off


# ===================================================================
# prefetcher lifecycle
# ===================================================================

def test_prefetcher_delivers_in_order_and_exhausts():
    from flexflow_tpu.engine import ChunkPrefetcher, PrefetchExhausted

    pf = ChunkPrefetcher(lambda c: c * 10, [1, 2, 3], depth=2)
    assert [pf.get(), pf.get(), pf.get()] == [10, 20, 30]
    with pytest.raises(PrefetchExhausted):
        pf.get(timeout=5)
    pf.shutdown()
    assert not pf.alive


def test_prefetcher_staging_error_propagates_to_consumer():
    from flexflow_tpu.engine import ChunkPrefetcher

    pf = ChunkPrefetcher(lambda c: 1 // 0, [1, 2], depth=1)
    with pytest.raises(ZeroDivisionError):
        pf.get(timeout=5)
    pf.shutdown()
    assert not pf.alive


def test_prefetcher_shutdown_unblocks_worker_on_full_queue():
    from flexflow_tpu.engine import ChunkPrefetcher

    # depth=1 and an unconsumed backlog: the worker blocks on put();
    # shutdown must still leave the thread dead (no leak)
    pf = ChunkPrefetcher(lambda c: c, list(range(50)), depth=1)
    assert pf.get(timeout=5) == 0
    pf.shutdown()
    assert not pf.alive


# ===================================================================
# equivalence: pipelined fit == eager fit, bit for bit
# ===================================================================

def _fit_with_spy(tmpdir, pipeline_steps, epochs=2, n=64):
    import jax

    x, y = _data(n)
    ff = _mlp()
    spy = _StepSpy()
    ff.enable_diagnostics(str(tmpdir), rules=[spy])
    ff.fit(x, y, epochs=epochs, batch_size=8, shuffle=True,
           pipeline_steps=pipeline_steps)
    return {
        "losses": [l for _, l in spy.records],
        "steps": [s for s, _ in spy.records],
        "weights": _weights(ff),
        "rng": np.asarray(jax.device_get(jax.random.key_data(ff._rng))),
        "step": int(np.asarray(jax.device_get(ff._step))),
        "counters": {k: np.asarray(v) for k, v in
                     jax.device_get(ff._counters).items()},
    }


@pytest.mark.parametrize("pipeline_steps", [4, 3],
                         ids=["even-chunks", "ragged-tail"])
def test_pipelined_fit_bit_identical_to_eager(tmp_path, pipeline_steps):
    """THE equivalence gate: 2 shuffled epochs, same seed — losses,
    params, RNG stream, step counters, and metric counters all match the
    eager loop bit-exactly (pipeline_steps=3 exercises the shorter tail
    chunk: 8 batches/epoch → chunks of 3+3+2)."""
    eager = _fit_with_spy(tmp_path / "eager", 1)
    piped = _fit_with_spy(tmp_path / "piped", pipeline_steps)

    assert eager["steps"] == piped["steps"] == list(range(1, 17))
    assert eager["losses"] == piped["losses"]  # bit-exact floats
    assert eager["step"] == piped["step"] == 16
    np.testing.assert_array_equal(eager["rng"], piped["rng"])
    for k in eager["weights"]:
        np.testing.assert_array_equal(
            eager["weights"][k], piped["weights"][k],
            err_msg=f"weight {k} diverged")
    for k in eager["counters"]:
        np.testing.assert_array_equal(
            eager["counters"][k], piped["counters"][k],
            err_msg=f"counter {k} diverged")


def test_pipelined_telemetry_artifacts_schema_valid(tmp_path):
    """Pipelined mode must keep every observability consumer working:
    per-step metrics records (full time split), step/data_wait/chunk
    trace spans, checkpoint records, and a doctor verdict of healthy."""
    import json

    from flexflow_tpu.diagnostics.doctor import diagnose
    from flexflow_tpu.telemetry import read_jsonl

    tdir = tmp_path / "t"
    x, y = _data(64)
    ff = _mlp(argv=["--telemetry-dir", str(tdir),
                    "--checkpoint-dir", str(tmp_path / "ck"),
                    "--checkpoint-every", "4",
                    "--pipeline-steps", "4"])
    ff.enable_telemetry(str(tdir))
    ff.fit(x, y, epochs=1, batch_size=8, shuffle=True)

    recs = read_jsonl(os.path.join(str(tdir), "metrics.jsonl"))
    steps = [r for r in recs if r["kind"] == "step"]
    assert [r["step"] for r in steps] == list(range(1, 9))
    for s in steps:
        for f in ("step_time_s", "data_wait_s", "save_latency_s",
                  "device_time_s", "ema_step_time_s"):
            assert f in s, f"step record missing {f}"
    assert [r for r in recs if r["kind"] == "checkpoint"], \
        "chunk-boundary saves must produce checkpoint records"
    summ = [r for r in recs if r["kind"] == "summary"][-1]
    assert summ["steps"] == 8 and summ["examples_per_sec"] > 0

    with open(os.path.join(str(tdir), "trace.json")) as f:
        names = {e["name"] for e in json.load(f)["traceEvents"]}
    for required in ("step", "data_wait", "chunk", "prefetch.stage"):
        assert required in names, f"trace missing {required!r}"

    d = diagnose(str(tdir))
    assert d["steps"] == 8
    assert d["checkpoints"]["count"] >= 1


# ===================================================================
# resilience at chunk boundaries
# ===================================================================

def test_pipelined_kill_resume_bit_identical(tmp_path):
    """Mid-chunk injected death → auto-resume lands on a chunk-edge
    cursor and the resumed pipelined run reproduces the uninterrupted
    EAGER run bit-exactly (the equivalence and the resume proven in one
    trajectory)."""
    import jax

    from flexflow_tpu.resilience import (
        FaultInjector, SimulatedPreemption, latest_checkpoint,
        load_checkpoint)

    x, y = _data(64)  # 8 batches/epoch
    root = str(tmp_path / "ck")

    ref = _mlp()
    ref.fit(x, y, epochs=2, batch_size=8, shuffle=True)  # eager, 16 steps
    ref_w = _weights(ref)

    # killed pipelined run: chunks of 4, checkpoint cadence 3 (hits mid-
    # chunk — the boundary save logic must still fire), die at step 6
    ff1 = _mlp(argv=["--checkpoint-dir", root, "--checkpoint-every", "3",
                     "--pipeline-steps", "4"])
    fault = FaultInjector(kill_after_step=6)
    ff1.set_fault_hook(fault)
    with pytest.raises(SimulatedPreemption):
        ff1.fit(x, y, epochs=2, batch_size=8, shuffle=True)
    assert fault.fired
    assert _no_prefetch_threads(), "prefetch thread leaked across the kill"
    del ff1

    last = latest_checkpoint(root)
    assert last is not None
    _, manifest = load_checkpoint(last)
    cur = manifest["extras"]["cursor"]
    assert cur["batch"] % 4 == 0, f"cursor {cur} not on a chunk edge"

    ff2 = _mlp(argv=["--checkpoint-dir", root, "--auto-resume",
                     "--pipeline-steps", "4"])
    ff2.fit(x, y, epochs=2, batch_size=8, shuffle=True)
    assert int(np.asarray(jax.device_get(ff2._step))) == 16
    got = _weights(ff2)
    for k in ref_w:
        np.testing.assert_array_equal(
            got[k], ref_w[k],
            err_msg=f"weight {k} diverged after kill/resume")


def test_pipelined_sigterm_drains_at_chunk_boundary(tmp_path):
    """A preemption notice mid-chunk lets the running chunk finish, then
    finalizes with one synchronous snapshot at the NEXT chunk edge — the
    cursor rounds to the boundary and fit returns early."""
    import jax

    from flexflow_tpu.resilience import latest_checkpoint, load_checkpoint

    x, y = _data(128)  # 16 batches/epoch → chunks of 4
    root = str(tmp_path / "ck")
    ff = _mlp(argv=["--checkpoint-dir", root, "--pipeline-steps", "4"])

    _handler_holder = [None]

    def notice(step):
        if step == 2:  # delivered during chunk 1's boundary processing
            _handler_holder[0].request()

    from flexflow_tpu.resilience import policy as pol

    orig_enter = pol.PreemptionHandler.__enter__

    def capture_enter(self):
        _handler_holder[0] = self
        return orig_enter(self)

    pol.PreemptionHandler.__enter__ = capture_enter
    try:
        ff.set_fault_hook(notice)
        ff.fit(x, y, epochs=2, batch_size=8, shuffle=True)  # returns early
    finally:
        pol.PreemptionHandler.__enter__ = orig_enter

    # notice landed after chunk 1 (steps 1-4); chunk 2 (5-8) runs, then
    # the boundary drains + final-saves: stopped at step 8, cursor batch 8
    assert int(np.asarray(jax.device_get(ff._step))) == 8
    last = latest_checkpoint(root)
    assert last is not None and last.endswith("step_00000008")
    _, manifest = load_checkpoint(last)
    assert manifest["extras"]["cursor"] == {"epoch": 0, "batch": 8}
    assert _no_prefetch_threads()


def test_pipelined_health_abort_shuts_prefetcher_down(tmp_path):
    """An abort-listed rule firing mid-chunk stops fit with HealthAbort
    and the prefetch thread is joined — no leak even though the epoch had
    chunks still staged/queued."""
    from flexflow_tpu.diagnostics import HealthAbort
    from flexflow_tpu.diagnostics.health import Alert, Rule

    class BoomRule(Rule):
        name = "boom"

        def _check(self, rec):
            if rec["step"] >= 3:
                return Alert(rule=self.name, level="warning",
                             step=int(rec["step"]), message="boom")
            return None

    x, y = _data(128)  # plenty of chunks left to strand in the queue
    ff = _mlp()
    ff.enable_diagnostics(str(tmp_path / "t"), rules=[BoomRule()],
                          abort_on=("boom",))
    with pytest.raises(HealthAbort):
        ff.fit(x, y, epochs=2, batch_size=8, shuffle=True,
               pipeline_steps=4)
    assert _no_prefetch_threads(), "prefetch thread leaked after HealthAbort"


# ===================================================================
# satellites: dataloader spec cache, health sampling cadence
# ===================================================================

def test_dataloader_caches_partition_spec_lookup():
    """next_batch_sharded resolved the input's spec by scanning
    graph.sources() EVERY batch; it must now resolve once and reuse."""
    ff = _mlp()
    data = np.random.RandomState(0).randn(32, 16).astype(np.float32)
    loader = ff.create_data_loader(ff._input_tensors[0], data)

    calls = []
    orig = ff.graph.sources

    def counting_sources():
        calls.append(1)
        return orig()

    ff.graph.sources = counting_sources
    try:
        b1 = loader.next_batch_sharded()
        b2 = loader.next_batch_sharded()
    finally:
        ff.graph.sources = orig
    assert len(calls) == 1, f"sources() scanned {len(calls)}× for 2 batches"
    np.testing.assert_array_equal(np.asarray(b1), data[:8])
    np.testing.assert_array_equal(np.asarray(b2), data[8:16])
    assert b1.sharding.spec == ff.graph.sources()[0].outputs[0].partition_spec()


def test_health_sample_every_thins_loss_fetch(tmp_path):
    """--health-sample-every 3: the eager loop fetches the loss (a full
    device drain) only on steps 3 and 6, and the rules see ONE record
    per 3-step window carrying the window AVERAGE — dispatch-only
    timings from the unsynced steps in between never reach the
    spike/stall/drift baselines raw."""
    x, y = _data(64)
    ff = _mlp(argv=["--health-sample-every", "3"])
    spy = _StepSpy()
    ff.enable_diagnostics(str(tmp_path / "t"), rules=[spy])
    ff.fit(x, y, epochs=1, batch_size=8, shuffle=True)  # 8 steps
    assert [s for s, _ in spy.records] == [3, 6]
    assert all(l is not None for _, l in spy.records)


def test_health_sample_every_default_keeps_per_step_records(tmp_path):
    """K=1 (default) reduces to the old behavior exactly: one record per
    step, every one carrying the loss."""
    x, y = _data(64)
    ff = _mlp()
    spy = _StepSpy()
    ff.enable_diagnostics(str(tmp_path / "t"), rules=[spy])
    ff.fit(x, y, epochs=1, batch_size=8, shuffle=True)
    assert [s for s, _ in spy.records] == list(range(1, 9))
    assert all(l is not None for _, l in spy.records)
