"""ffrules substitution-rule verifier tests (analysis/rules.py).

Covers: the full-registry sweep (all five passes clean on the CI mesh),
registry determinism + the content fingerprint, the corruption self-test
corpus (each unsound-rule class caught as exactly its class), the JSON
load gate (structured refusal naming rule + class, --no-verify-rules
downgrade, verdict in the compile report), the JSON loader's error
paths, the rules component of the warm-start plan fingerprint, the
`unverified_rule_load` lint rule, and the executor-crash regression the
oracle caught in partition_add_combine.
"""

import json

import numpy as np
import pytest

CI_MESH = {"data": 2, "model": 4, "dcn": 1, "seq": 1}


def _mk_config(argv=()):
    import sys

    old = sys.argv
    sys.argv = ["t", *argv]
    try:
        from flexflow_tpu import FFConfig

        return FFConfig()
    finally:
        sys.argv = old


# ------------------------------------------------------------- registry

def test_registry_stable_sorted_deduped():
    """Pass 5: two generator runs serialize identically, names are
    sorted and unique, and the fingerprint is a stable content hash."""
    from types import SimpleNamespace

    from flexflow_tpu.analysis.rules import (
        rules_fingerprint,
        serialize_rule,
    )
    from flexflow_tpu.search.substitution import generate_all_pcg_xfers

    config = _mk_config(["-b", "8"])
    mesh = SimpleNamespace(shape=dict(CI_MESH))
    a = generate_all_pcg_xfers(mesh, config)
    b = generate_all_pcg_xfers(mesh, config)
    sa = [json.dumps(serialize_rule(x), sort_keys=True) for x in a]
    sb = [json.dumps(serialize_rule(x), sort_keys=True) for x in b]
    assert sa == sb
    names = [x.name for x in a]
    assert names == sorted(names)
    assert len(set(names)) == len(names)
    assert rules_fingerprint(a) == rules_fingerprint(b)
    # dropping any one rule changes the content address
    assert rules_fingerprint(a[1:]) != rules_fingerprint(a)


def test_full_registry_verifies_clean():
    """The acceptance sweep: every generated rule for the CI mesh config
    passes all per-rule passes (symbolic transfer, parallel state,
    oracle, fuzz) with zero errors AND zero unverified-rule warnings."""
    from flexflow_tpu.analysis.rules import verify_registry

    config = _mk_config(["-b", "8"])
    res = verify_registry(CI_MESH, config)
    assert res.errors() == [], [str(f) for f in res.errors()]
    assert res.warnings() == [], [str(f) for f in res.warnings()]
    clean = res.by_code("rules_clean")
    assert clean and clean[0].details["rules"] > 15
    assert len(clean[0].details["fingerprint"]) == 64


def test_moe_fusion_rule_verifies():
    """The data-driven fuse_moe_trio family instantiates (Group_by ->
    n Dense -> Aggregate), verifies structurally, and skips the oracle
    with an explicit info finding (fresh Experts weights)."""
    from flexflow_tpu.analysis.rules import verify_rule
    from flexflow_tpu.search.substitution import create_fuse_moe_trio

    findings = verify_rule(create_fuse_moe_trio(4), CI_MESH)
    assert [f for f in findings if f.severity == "error"] == []
    assert any(f.code == "rule_oracle_skipped" for f in findings)


# ------------------------------------------------- corruption self-test

def test_corruption_classes_each_caught_as_its_class():
    """The >=6-class self-test corpus: every injected unsound rule is
    caught, and the ONLY finding code emitted is its own class."""
    from flexflow_tpu.analysis.rules import selftest_classes, verify_rule

    corpus = selftest_classes()
    assert len(corpus) >= 6
    for klass, xfer, expect in corpus:
        findings = verify_rule(xfer, CI_MESH)
        codes = sorted({f.code for f in findings})
        assert codes == [expect], (klass, codes)
        assert all(f.severity == "error" for f in findings), klass


def test_partial_sum_generalization_covers_whole_registry():
    """The one-rule numerics test (test_partial_sum_through_nonlinear
    _rejected) generalized: the verifier's nonlinear probe fires on ANY
    rule whose mapped output carries partial sums."""
    from flexflow_tpu.analysis.rules import selftest_classes, verify_rule

    _, xfer, expect = next(
        c for c in selftest_classes() if c[0] == "partial_sum_nonlinear")
    findings = verify_rule(xfer, CI_MESH)
    assert [f.code for f in findings] == [expect]


# ------------------------------------------------------- JSON load gate

_BAD_RULE = {
    "name": "external_bad_activation",
    "src": [{"op": "linear", "inputs": ["$0"], "out": "l1",
             "constraints": [{"attr": "activation", "eq": "none"}]}],
    "dst": [{"op": "linear", "inputs": ["$0"], "match": "l1",
             "params_update": {"activation": "sigmoid"}, "out": "l2"}],
    "map_outputs": [["l1", "l2"]],
}

_GOOD_RULES = {"rules": [
    {"generator": "replicate_linear_combine", "degree": 2,
     "activation": "none"},
    {"generator": "linear_relu_merge"},
]}


def test_unsound_json_rule_refused_at_load(tmp_path):
    from types import SimpleNamespace

    from flexflow_tpu.analysis.rules import RuleVerificationError
    from flexflow_tpu.search.substitution import load_rule_collection

    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"rules": [_BAD_RULE]}))
    config = _mk_config(["-b", "8"])
    mesh = SimpleNamespace(shape=dict(CI_MESH))
    with pytest.raises(RuleVerificationError) as ei:
        load_rule_collection(str(p), mesh, config=config)
    # structured refusal names the rule AND the finding class
    assert "external_bad_activation" in str(ei.value)
    assert "rule_numeric_divergence" in str(ei.value)
    assert ei.value.result.errors()
    # without config (fingerprint-only path) the loader stays permissive
    assert len(load_rule_collection(str(p), mesh)) == 1


def test_no_verify_rules_downgrades_and_records(tmp_path):
    import os
    from types import SimpleNamespace

    from flexflow_tpu.analysis.rules import _LOAD_RESULTS
    from flexflow_tpu.search.substitution import load_rule_collection

    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"rules": [_BAD_RULE]}))
    config = _mk_config(["-b", "8", "--no-verify-rules"])
    assert config.verify_rules is False
    mesh = SimpleNamespace(shape=dict(CI_MESH))
    xfers = load_rule_collection(str(p), mesh, config=config)
    assert len(xfers) == 1
    recorded = _LOAD_RESULTS[os.path.abspath(str(p))]
    assert recorded.errors()  # verdict recorded even though downgraded


def test_compile_refuses_unsound_json_rule(tmp_path):
    from flexflow_tpu import FFModel, LossType, SGDOptimizer
    from flexflow_tpu.analysis.rules import RuleVerificationError

    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"rules": [_BAD_RULE]}))
    config = _mk_config(["-b", "8", "--mesh", "2,2,1,1",
                         "--substitution-json", str(p), "--budget", "4"])
    ff = FFModel(config)
    x = ff.create_tensor((8, 32), name="rg_in")
    ff.dense(x, 8, name="rg_fc")
    with pytest.raises(RuleVerificationError):
        ff.compile(optimizer=SGDOptimizer(lr=0.1),
                   loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)


def test_compile_gate_records_verdict_in_report(tmp_path):
    """--no-verify-rules: the unsound rule loads, the compile completes,
    and the downgraded verdict + rule-set fingerprint land in the
    analysis section (strategy_report.json's source of truth)."""
    from flexflow_tpu import FFModel, LossType, SGDOptimizer

    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"rules": [_BAD_RULE]}))
    config = _mk_config(["-b", "8", "--mesh", "2,2,1,1",
                         "--substitution-json", str(p), "--budget", "4",
                         "--no-verify-rules"])
    ff = FFModel(config)
    x = ff.create_tensor((8, 32), name="rgd_in")
    ff.dense(x, 8, name="rgd_fc")
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    res = ff._analysis
    assert "rule_verify" in res.passes_run
    recorded = res.by_code("rule_numeric_divergence")
    assert recorded and all(f.severity == "warning" for f in recorded)
    fp = res.by_code("rules_fingerprint")
    assert fp and fp[0].details["source"] == "json"


def test_compile_clean_json_reports_fingerprint(tmp_path):
    from flexflow_tpu import FFModel, LossType, SGDOptimizer

    p = tmp_path / "good.json"
    p.write_text(json.dumps(_GOOD_RULES))
    config = _mk_config(["-b", "8", "--mesh", "2,2,1,1",
                         "--substitution-json", str(p), "--budget", "4"])
    ff = FFModel(config)
    x = ff.create_tensor((8, 32), name="rgc_in")
    ff.dense(x, 8, name="rgc_fc")
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    res = ff._analysis
    assert res.by_code("rules_clean")
    assert res.by_code("rules_fingerprint")
    assert not res.errors()


# -------------------------------------------------- loader error paths

def test_loader_error_paths(tmp_path):
    """A malformed rule file raises a clear ValueError naming the
    problem — never a KeyError mid-search or silent corruption."""
    from types import SimpleNamespace

    from flexflow_tpu.search.substitution import load_rule_collection

    mesh = SimpleNamespace(shape=dict(CI_MESH))

    def load(payload):
        p = tmp_path / "r.json"
        p.write_text(json.dumps(payload))
        return load_rule_collection(str(p), mesh)

    # unknown op name
    with pytest.raises(ValueError, match="unknown op type"):
        load({"rules": [{"name": "r", "src": [{"op": "nope"}],
                         "dst": [], "map_outputs": []}]})
    # dangling TensorX input (references an undeclared op)
    with pytest.raises(ValueError, match="references unknown op"):
        load({"rules": [{"name": "r",
                         "src": [{"op": "linear", "inputs": ["ghost"],
                                  "out": "l1"}],
                         "dst": [], "map_outputs": []}]})
    # empty dst
    with pytest.raises(ValueError, match="needs src ops, dst ops"):
        load({"rules": [{"name": "r",
                         "src": [{"op": "linear", "inputs": ["$0"],
                                  "out": "l1"}],
                         "dst": [], "map_outputs": [["l1", "l1"]]}]})
    # parallel dst op missing a params field
    with pytest.raises(ValueError, match="missing field"):
        load({"rules": [{"name": "r",
                         "src": [{"op": "linear", "inputs": ["$0"],
                                  "out": "l1"}],
                         "dst": [{"op": "repartition", "inputs": ["$0"],
                                  "params": {"dim": 0}, "out": "p1"}],
                         "map_outputs": [["l1", "p1"]]}]})
    # dst compute op with neither match nor parallel params
    with pytest.raises(ValueError, match="needs 'match'"):
        load({"rules": [{"name": "r",
                         "src": [{"op": "linear", "inputs": ["$0"],
                                  "out": "l1"}],
                         "dst": [{"op": "linear", "inputs": ["$0"],
                                  "out": "l2"}],
                         "map_outputs": [["l1", "l2"]]}]})
    # map_outputs referencing an unknown op
    with pytest.raises(ValueError, match="map_outputs references"):
        load({"rules": [{"name": "r",
                         "src": [{"op": "linear", "inputs": ["$0"],
                                  "out": "l1"}],
                         "dst": [{"op": "linear", "inputs": ["$0"],
                                  "match": "l1", "out": "l2"}],
                         "map_outputs": [["l1", "ghost"]]}]})
    # a rule that is not an object
    with pytest.raises(ValueError, match="must be an object"):
        load({"rules": ["not-a-rule"]})
    # the file's rules field is not a list
    with pytest.raises(ValueError, match="'rules' list"):
        load({"rules": {"generator": "linear_relu_merge"}})
    # constraint without eq/mod
    with pytest.raises(ValueError, match="'eq' or 'mod'"):
        load({"rules": [{"name": "r",
                         "src": [{"op": "linear", "inputs": ["$0"],
                                  "out": "l1",
                                  "constraints": [{"attr": "x"}]}],
                         "dst": [{"op": "linear", "inputs": ["$0"],
                                  "match": "l1", "out": "l2"}],
                         "map_outputs": [["l1", "l2"]]}]})
    # parallel param of the wrong type (a string degree would otherwise
    # crash the shape transforms mid-verification/mid-search)
    with pytest.raises(ValueError, match="must be an integer"):
        load({"rules": [{"name": "r",
                         "src": [{"op": "linear", "inputs": ["$0"],
                                  "out": "l1"}],
                         "dst": [{"op": "repartition", "inputs": ["$0"],
                                  "params": {"dim": 0, "degree": "x"},
                                  "out": "p1"}],
                         "map_outputs": [["l1", "p1"]]}]})


# ------------------------------------------------ plan fingerprint join

def test_changed_rule_set_invalidates_plan_fingerprint(monkeypatch):
    """The rules_fingerprint is a component of the structural plan
    fingerprint: a changed built-in registry (new/removed/altered rule)
    changes the plan address, so the warm-start plan cache misses and
    re-searches instead of replaying a plan searched under stale rules."""
    from flexflow_tpu import FFModel, LossType, SGDOptimizer
    from flexflow_tpu.search import substitution as S
    from flexflow_tpu.warmstart.fingerprint import structural_fingerprint

    config = _mk_config(["-b", "8", "--mesh", "2,2,1,1"])
    ff = FFModel(config)
    x = ff.create_tensor((8, 32), name="fpr_in")
    ff.dense(x, 8, name="fpr_fc")
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    mesh_axes = {k: int(v) for k, v in ff.mesh.shape.items()}
    before = structural_fingerprint(ff.graph, mesh_axes, config)
    assert before == structural_fingerprint(ff.graph, mesh_axes, config)

    real = S.generate_all_pcg_xfers

    def altered(mesh, cfg, graph=None):
        xfers = real(mesh, cfg, graph)
        return xfers[:-1]  # one rule removed = a different rule set

    monkeypatch.setattr(S, "generate_all_pcg_xfers", altered)
    after = structural_fingerprint(ff.graph, mesh_axes, config)
    assert after != before


def test_json_rule_file_content_keys_fingerprint(tmp_path):
    """--substitution-json compiles key the plan address by the LOADED
    rule content too (rules component), not just the file digest."""
    from flexflow_tpu.warmstart.fingerprint import rules_signature

    p = tmp_path / "rules.json"
    p.write_text(json.dumps(_GOOD_RULES))
    config = _mk_config(["-b", "8", "--mesh", "2,2,1,1",
                         "--substitution-json", str(p)])
    a = rules_signature(None, CI_MESH, config)
    p.write_text(json.dumps({"rules": _GOOD_RULES["rules"][:1]}))
    b = rules_signature(None, CI_MESH, config)
    assert a != b and not a.startswith("unloadable")
    # an unloadable file is its own distinct state, never a crash
    p.write_text("{broken")
    assert rules_signature(None, CI_MESH, config).startswith("unloadable")


# ----------------------------------------------------------- lint rule

_UNGATED_SNIPPET = """
def inject(path, mesh):
    from flexflow_tpu.search.substitution import load_rule_collection
    return load_rule_collection(path, mesh)
"""

_GATED_SNIPPET = """
def inject(path, mesh, config):
    from flexflow_tpu.search.substitution import load_rule_collection
    return load_rule_collection(path, mesh, config=config)
"""

_CHECKER_SNIPPET = """
def inject(mesh, config):
    from flexflow_tpu.analysis.rules import verify_rules
    from flexflow_tpu.search.substitution import generate_all_pcg_xfers
    xfers = generate_all_pcg_xfers(mesh, config)
    verify_rules(xfers, mesh)
    return xfers
"""

_PRAGMA_SNIPPET = """
def inject(mesh, config):
    from flexflow_tpu.search.substitution import generate_all_pcg_xfers
    return generate_all_pcg_xfers(mesh, config)  # fflint: ok unverified_rule_load
"""

_NONE_CONFIG_SNIPPET = """
def inject(path, mesh):
    from flexflow_tpu.search.substitution import load_rule_collection
    return load_rule_collection(path, mesh, config=None)
"""


def test_lint_unverified_rule_load():
    from flexflow_tpu.analysis import lint

    def codes(src):
        return [f.code for f in lint.lint_source(
            src, "snippet.py", select=("unverified_rule_load",))]

    assert codes(_UNGATED_SNIPPET) == ["unverified_rule_load"]
    assert codes(_GATED_SNIPPET) == []       # config= IS the gate
    assert codes(_CHECKER_SNIPPET) == []     # verifier consulted
    assert codes(_PRAGMA_SNIPPET) == []      # explicit suppression
    # a literal config=None loads UNVERIFIED — not a gate
    assert codes(_NONE_CONFIG_SNIPPET) == ["unverified_rule_load"]


def test_fflint_repo_clean_includes_rule_load():
    """Tier-1 invariant: the repo itself carries no ungated rule-load
    sites (the generators' own fixtures are pragma'd)."""
    import os

    from flexflow_tpu.analysis.lint import lint_paths

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = lint_paths(
        [os.path.join(root, "flexflow_tpu"),
         os.path.join(root, "scripts")],
        select=("unverified_rule_load",))
    assert findings == [], [str(f) for f in findings]


def test_crashing_rule_refused_structurally():
    """A rule that makes verification itself crash is refused with the
    structured rule_verification_crash error — never a raw traceback
    through the load gate."""
    from flexflow_tpu.analysis.rules import verify_rules

    class _Broken:
        name = "broken_rule"
        # no src_ops/dst_ops/mapped_outputs — serialization/verification
        # will raise AttributeError, the crash path

    res = verify_rules([_Broken()], CI_MESH)
    errs = res.errors()
    assert errs and errs[0].code == "rule_verification_crash"
    assert "broken_rule" in errs[0].where


def test_rule_verify_pass_skips_manual_and_import_plans():
    """The compile pass stamps no rules_fingerprint on plans no rewrite
    search produced (manual/import), and does stamp budget-searched
    compiles (no JSON, no --enable-substitutions needed)."""
    from types import SimpleNamespace

    from flexflow_tpu.analysis import rules as R

    config = _mk_config(["-b", "8", "--budget", "6"])
    mesh = SimpleNamespace(shape=dict(CI_MESH))
    searched = SimpleNamespace(config=config, plan_source="search")
    stamped = R.run(None, mesh, searched)
    assert any(f.code == "rules_fingerprint"
               and f.details["source"] == "generated" for f in stamped)
    for src in ("manual", "import"):
        ctx = SimpleNamespace(config=config, plan_source=src)
        assert R.run(None, mesh, ctx) == []


# ------------------------------------------------------- regressions

def test_cast_propagates_target_dtype():
    """propagate_parallel_state carries OP_CAST's target dtype (the
    symbolic dtype-transfer pass depends on it)."""
    from flexflow_tpu.fftype import DataType, OperatorType as OT
    from flexflow_tpu.ops.shape_ops import CastParams
    from flexflow_tpu.pcg.graph import Graph, OpNode
    from flexflow_tpu.search.substitution import propagate_parallel_state
    from flexflow_tpu.tensor import ParallelTensor, ParallelTensorShape

    g = Graph()
    inp = g.add_node(OpNode(OT.OP_INPUT, None, name="x"))
    inp.outputs = [ParallelTensor(ParallelTensorShape.from_shape(
        (8, 8), DataType.DT_FLOAT), name="x")]
    cast = g.add_node(OpNode(OT.OP_CAST,
                             CastParams(DataType.DT_BFLOAT16)))
    g.add_edge(inp, cast, 0, 0)
    propagate_parallel_state(g)
    assert cast.outputs[0].dtype == DataType.DT_BFLOAT16


def test_partition_add_combine_rewrite_executes():
    """Regression for the bug the oracle caught: the rewritten add node
    must inherit the matched node's params (match_src) — params=None
    crashes the executor's _binary_forward at runtime."""
    from flexflow_tpu import FFModel, LossType, SGDOptimizer
    from flexflow_tpu.fftype import OperatorType as OT
    from flexflow_tpu.search.substitution import (
        create_partition_add_combine,
    )

    config = _mk_config(["-b", "8", "--mesh", "2,1,1,1"])
    ff = FFModel(config)
    x = ff.create_tensor((8, 32), name="par_in")
    a = ff.dense(x, 32, name="par_fc1")
    b = ff.dense(x, 32, name="par_fc2")
    ff.add(a, b, name="par_add")
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_IDENTITY)
    xfer = create_partition_add_combine(2, ("data",))
    matches = xfer.find_matches(ff.graph)
    assert matches
    ng = xfer.apply(ff.graph, matches[0])
    add = next(n for n in ng.topo_order() if n.op_type == OT.OP_EW_ADD)
    assert add.params is not None


def test_oracle_executes_whole_registry_families():
    """Spot-check the oracle end-to-end on the three structurally
    distinct families: algebraic merge, column TP with Reduction, and
    sample partition (fast subset of the scripts/ffrules.py sweep)."""
    from flexflow_tpu.analysis.rules import _check_oracle, _dim_env
    from flexflow_tpu.fftype import ActiMode
    from flexflow_tpu.search.substitution import (
        create_linear_relu_merge,
        create_partition_softmax_combine,
        create_replicate_attention_reduce,
    )

    for xfer in (create_linear_relu_merge(),
                 create_replicate_attention_reduce(4, ("model",)),
                 create_partition_softmax_combine(2, ("data",))):
        findings = _check_oracle(xfer, _dim_env(4, "oracle"),
                                 f"rule:{xfer.name}")
        assert findings == [], (xfer.name,
                                [str(f) for f in findings])
