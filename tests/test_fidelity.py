"""Cost-model fidelity CI leg (CPU mesh; the real-chip battery is
scripts/cost_model_fidelity.py → FIDELITY_r05.json). The search only needs
RANKING fidelity to pick the right plan, so the real-chip artifact's
headline number is Spearman rank correlation between composed predictions
and measured step times. On a shared CI CPU, however, the two smallest
configs are dispatch-dominated and their wall-clock order flips under
machine noise (the long-standing flake), so the CI assertions are split:
the PREDICTION ordering is deterministic and asserted exactly, while the
only wall-clock fact asserted is a generous monotonic bound between the
battery's extremes (~30x FLOPs apart — an inversion there would mean the
measurement harness itself is broken, not that the machine was busy)."""


def test_fidelity_rank_correlation_and_calibration():
    import sys

    sys.path.insert(0, "/root/repo")
    from scripts.cost_model_fidelity import (
        _lm,
        _spearman,
        run_fidelity,
    )

    configs = [
        _lm("lm_h64_s32_b4", 64, 4, 2, 32, 4, "xla", vocab=256),
        _lm("lm_h128_s64_b4", 128, 4, 2, 64, 4, "xla", vocab=256),
        _lm("lm_h256_s64_b8", 256, 4, 4, 64, 8, "xla", vocab=256),
    ]
    rep = run_fidelity(configs, steps=3, calibrate_top_k=4)
    rows = {r["name"]: r for r in rep["configs"]}
    # deterministic proxy for ranking fidelity: the composed analytic
    # predictions must order the size-separated family exactly — this is
    # what the search consumes, and it involves no wall clock at all
    assert (rows["lm_h64_s32_b4"]["predicted_ms"]
            < rows["lm_h128_s64_b4"]["predicted_ms"]
            < rows["lm_h256_s64_b8"]["predicted_ms"]), rep
    # generous monotonic bound on the measurement harness: the ~30x-FLOPs
    # config must not measure FASTER than the smallest. Adjacent configs
    # are deliberately NOT compared (dispatch-bound CPU times are noise-
    # ordered); the fine-grained ranking lives in the real-chip artifact.
    assert (rows["lm_h256_s64_b8"]["measured_ms"]
            >= rows["lm_h64_s32_b4"]["measured_ms"]), rep
    # calibration ran and changed the composed prediction (its absolute
    # accuracy is only meaningful on the real chip — the cpu ChipSpec is a
    # placeholder and XLA:CPU step overhead dwarfs per-op kernel time; the
    # error-shrink demonstration lives in the FIDELITY_r05.json artifact)
    for row in rep["configs"]:
        assert row["predicted_calibrated_ms"] > 0
        assert (row["predicted_calibrated_ms"] != row["predicted_ms"]), row


def test_spearman_helper():
    from scripts.cost_model_fidelity import _spearman

    assert _spearman([1, 2, 3], [10, 20, 30]) == 1.0
    assert _spearman([1, 2, 3], [30, 20, 10]) == -1.0
    assert _spearman([1, 1, 1], [1, 2, 3]) == 0.0
