"""Cost-model fidelity CI leg (CPU mesh; the real-chip battery is
scripts/cost_model_fidelity.py → FIDELITY_r05.json). The search only needs
RANKING fidelity to pick the right plan, so the assertion is rank
correlation between composed predictions and measured step times; absolute
CPU times are meaningless against the analytic cpu ChipSpec (XLA:CPU is
not the modeled machine), which is exactly why the artifact's headline
numbers come from the real chip."""


def test_fidelity_rank_correlation_and_calibration():
    import sys

    sys.path.insert(0, "/root/repo")
    from scripts.cost_model_fidelity import (
        _lm,
        _spearman,
        run_fidelity,
    )

    configs = [
        _lm("lm_h64_s32_b4", 64, 4, 2, 32, 4, "xla", vocab=256),
        _lm("lm_h128_s64_b4", 128, 4, 2, 64, 4, "xla", vocab=256),
        _lm("lm_h256_s64_b8", 256, 4, 4, 64, 8, "xla", vocab=256),
    ]
    rep = run_fidelity(configs, steps=3, calibrate_top_k=4)
    # size-separated same-family configs: predicted ordering must match
    # measured ordering exactly — ranking is what the search consumes
    assert rep["spearman"] >= 0.99, rep
    assert rep["spearman_calibrated"] >= 0.99, rep
    # calibration ran and changed the composed prediction (its absolute
    # accuracy is only meaningful on the real chip — the cpu ChipSpec is a
    # placeholder and XLA:CPU step overhead dwarfs per-op kernel time; the
    # error-shrink demonstration lives in the FIDELITY_r05.json artifact)
    for row in rep["configs"]:
        assert row["predicted_calibrated_ms"] > 0
        assert (row["predicted_calibrated_ms"] != row["predicted_ms"]), row


def test_spearman_helper():
    from scripts.cost_model_fidelity import _spearman

    assert _spearman([1, 2, 3], [10, 20, 30]) == 1.0
    assert _spearman([1, 2, 3], [30, 20, 10]) == -1.0
    assert _spearman([1, 1, 1], [1, 2, 3]) == 0.0
