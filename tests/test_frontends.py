"""Frontend tests: Keras (Sequential + functional), torch.fx conversion
(values vs torch), .ff file round-trip (SURVEY §2.5)."""

import sys

import numpy as np
import pytest


def _reset_argv():
    sys.argv = ["test"]


def test_keras_sequential_trains():
    _reset_argv()
    from flexflow_tpu.keras import Dense, Sequential
    from flexflow_tpu.keras.optimizers import SGD

    model = Sequential([
        Dense(64, input_shape=(32,), activation="relu"),
        Dense(10, activation="softmax"),
    ])
    model.compile(optimizer=SGD(learning_rate=0.1),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    rs = np.random.RandomState(0)
    centers = rs.randn(10, 32) * 3
    y = rs.randint(0, 10, 1024)
    x = (centers[y] + rs.randn(1024, 32)).astype(np.float32)
    model.fit(x, y.reshape(-1, 1).astype(np.int32), epochs=2)
    acc = model.ffmodel.get_perf_metrics().get_accuracy()
    assert acc >= 0.9, acc


def test_keras_functional_merge():
    _reset_argv()
    from flexflow_tpu.keras import Concatenate, Dense, Input, Model

    a = Input(shape=(16,), batch_size=8)
    b = Input(shape=(16,), batch_size=8)
    x1 = Dense(8, activation="relu")(a)
    x2 = Dense(8, activation="relu")(b)
    merged = Concatenate(axis=1)([x1, x2])
    out = Dense(4, activation="softmax")(merged)
    model = Model(inputs=[a, b], outputs=out)
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy")
    rs = np.random.RandomState(0)
    xs = [rs.randn(8, 16).astype(np.float32) for _ in range(2)]
    ys = rs.randint(0, 4, (8, 1)).astype(np.int32)
    model.fit(xs, ys, epochs=1, batch_size=8)


def test_keras_cnn_builds():
    _reset_argv()
    from flexflow_tpu.keras import (
        Conv2D, Dense, Flatten, MaxPooling2D, Sequential,
    )

    model = Sequential([
        Conv2D(8, 3, strides=1, padding="same", activation="relu",
               input_shape=(1, 28, 28)),
        MaxPooling2D(2),
        Flatten(),
        Dense(10, activation="softmax"),
    ])
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy")
    out_dims = model.ffmodel.layers[-1].outputs[0].dims
    assert out_dims[-1] == 10


def test_torch_fx_mlp_matches_torch():
    """fx-converted model with installed weights must reproduce torch's
    forward numerics."""
    _reset_argv()
    import torch
    import torch.nn as nn

    from flexflow_tpu import CompMode, FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.torch_frontend import PyTorchModel

    torch.manual_seed(0)

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(20, 32)
            self.act = nn.ReLU()
            self.fc2 = nn.Linear(32, 6)

        def forward(self, x):
            h = self.act(self.fc1(x))
            return self.fc2(h) + 1.0

    net = Net().eval()
    config = FFConfig()
    config.batch_size = 4
    ff = FFModel(config)
    x = ff.create_tensor((4, 20), name="x")
    conv = PyTorchModel(net)
    (out,) = conv.torch_to_ff(ff, [x])
    t = ff.softmax(out, name="sm")
    ff.compile(optimizer=SGDOptimizer(lr=0.0),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    conv.install_weights(ff)

    rs = np.random.RandomState(0)
    xin = rs.randn(4, 20).astype(np.float32)
    ff.start_batch({"x": xin}, np.zeros((4, 1), np.int32))
    probs = np.asarray(ff.forward())
    with torch.no_grad():
        t_logits = net(torch.from_numpy(xin)).numpy()
    t_probs = np.exp(t_logits) / np.exp(t_logits).sum(-1, keepdims=True)
    np.testing.assert_allclose(probs, t_probs, rtol=1e-4, atol=1e-5)


def test_torch_fx_cnn_converts():
    _reset_argv()
    import torch.nn as nn

    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.torch_frontend import PyTorchModel

    net = nn.Sequential(
        nn.Conv2d(1, 4, 3, padding=1),
        nn.ReLU(),
        nn.MaxPool2d(2),
        nn.Flatten(),
        nn.Linear(4 * 14 * 14, 10),
        nn.Softmax(dim=-1),
    )
    config = FFConfig()
    config.batch_size = 2
    ff = FFModel(config)
    x = ff.create_tensor((2, 1, 28, 28), name="x")
    (out,) = PyTorchModel(net).torch_to_ff(ff, [x])
    assert out.dims == (2, 10)


def test_torch_ff_file_roundtrip(tmp_path):
    _reset_argv()
    import torch.nn as nn

    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.torch_frontend import PyTorchModel, torch_to_flexflow

    net = nn.Sequential(
        nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8), nn.Softmax(dim=-1),
    )
    path = str(tmp_path / "net.ff")
    torch_to_flexflow(net, path)

    config = FFConfig()
    config.batch_size = 4
    ff = FFModel(config)
    x = ff.create_tensor((4, 16), name="x")
    (out,) = PyTorchModel(path).torch_to_ff(ff, [x])
    assert out.dims == (4, 8)
    from flexflow_tpu.fftype import OperatorType as OT

    kinds = [l.op_type for l in ff.layers]
    assert kinds == [OT.OP_LINEAR, OT.OP_RELU, OT.OP_LINEAR, OT.OP_SOFTMAX]


def test_keras_shared_layer():
    """A layer called twice (weight-style sharing pattern) must keep both
    edges in the functional graph."""
    _reset_argv()
    from flexflow_tpu.keras import Add, Dense, Input, Model

    a = Input(shape=(16,), batch_size=8)
    b = Input(shape=(16,), batch_size=8)
    d = Dense(8, activation="relu", name="shared")
    y1 = d(a)
    y2 = d(b)
    out = Dense(4, activation="softmax")(Add()([y1, y2]))
    model = Model(inputs=[a, b], outputs=out)
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy")
    from flexflow_tpu.fftype import OperatorType as OT

    denses = [l for l in model.ffmodel.layers if l.op_type == OT.OP_LINEAR]
    assert len(denses) == 3  # two materialized calls + head


def test_torch_fx_cat_and_global_mean():
    _reset_argv()
    import torch
    import torch.nn as nn

    from flexflow_tpu import FFConfig, FFModel
    from flexflow_tpu.torch_frontend import PyTorchModel

    class Net(nn.Module):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(8, 8)
            self.fc2 = nn.Linear(8, 8)

        def forward(self, x):
            z = torch.cat([self.fc1(x), self.fc2(x)], dim=1)
            return torch.mean(z)

    config = FFConfig()
    config.batch_size = 4
    ff = FFModel(config)
    x = ff.create_tensor((4, 8), name="x")
    (out,) = PyTorchModel(Net()).torch_to_ff(ff, [x])
    assert out.dims == ()
