"""Joint Unity search tests: rewrites × placement DP in one optimizer
(reference base_optimize + Graph::optimal_cost, substitution.cc:2229-2311 +
graph.cc:1742-1843). Verifies the joint search is never worse than either
half alone, that sequence-splitting bounds wall time on a bench-scale LM,
and that a jointly-searched model still trains to convergence."""

import sys
import time

import numpy as np
import pytest


def _config(mesh_axes, batch=16, argv=()):
    sys.argv = ["test"] + list(argv)
    from flexflow_tpu import FFConfig

    config = FFConfig()
    config.mesh_axis_sizes = mesh_axes
    config.batch_size = batch
    return config


def _build_transformer_graph(config, layers=2):
    """Small encoder stack (attention + MLP) as a PCG, logits marked."""
    from flexflow_tpu import ActiMode, FFModel

    ff = FFModel(config)
    x = ff.create_tensor((config.batch_size, 32, 64), name="x")
    t = x
    for i in range(layers):
        a = ff.multihead_attention(t, t, t, 64, 4, name=f"l{i}_attn")
        t = ff.dense(a, 256, ActiMode.AC_MODE_RELU, name=f"l{i}_ffn1")
        t = ff.dense(t, 64, name=f"l{i}_ffn2")
    t = ff.dense(t, 16, name="head")
    return ff, t


def _pcg_of(ff):
    """Lower the builder's layers to a PCG without compiling (mirrors the
    compile() lowering)."""
    from flexflow_tpu.fftype import OperatorType as OT
    from flexflow_tpu.pcg.graph import Graph, OpNode
    from flexflow_tpu.tensor import ParallelTensor, ParallelTensorShape

    g = Graph()
    tensor_to_out = {}
    for t in ff._input_tensors:
        node = OpNode(OT.OP_INPUT, None, name=t.name)
        shape = ParallelTensorShape.from_shape(t.dims, t.dtype)
        node.outputs = [ParallelTensor(shape, name=t.name)]
        g.add_node(node)
        tensor_to_out[t.tensor_guid] = (node, 0)
    for layer in ff.layers:
        node = OpNode(layer.op_type, layer.params, name=layer.name,
                      layer_guid=layer.layer_guid,
                      initializers=layer.initializers)
        g.add_node(node)
        for dst_idx, t_in in enumerate(layer.inputs):
            src_node, src_idx = tensor_to_out[t_in.tensor_guid]
            g.add_edge(src_node, node, src_idx, dst_idx)
            node.inputs.append(src_node.outputs[src_idx])
        in_shapes = [t.dims for t in layer.inputs]
        node.weight_specs = node.op_def.weights(layer.params, in_shapes)
        for i, t_out in enumerate(layer.outputs):
            shape = ParallelTensorShape.from_shape(t_out.dims, t_out.dtype)
            pt = ParallelTensor(shape, name=t_out.name)
            pt.owner_op, pt.owner_idx = node, i
            node.outputs.append(pt)
            tensor_to_out[t_out.tensor_guid] = (node, i)
    return g


def _mesh_for(config):
    from flexflow_tpu.machine import build_mesh

    return build_mesh(config.mesh_shape())


def _joint_cost_of(graph, mesh, config, cm):
    from flexflow_tpu.search.joint import derive_pinned_configs
    from flexflow_tpu.search.unity import UnitySearch

    us = UnitySearch(graph, mesh, config, cm,
                     pinned=derive_pinned_configs(graph, mesh))
    choice = us.run()
    t, mem = us.evaluate(choice)
    return us._memory_penalized(t, mem)


def test_joint_beats_both_halves_transformer():
    """The joint optimum must cost <= the substitution-only result and <=
    the placement-DP-only result on the same transformer PCG."""
    config = _config((2, 4, 1, 1),
                     argv=["--budget", "8"])
    ff, _ = _build_transformer_graph(config)
    mesh = _mesh_for(config)

    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.joint import joint_graph_optimize
    from flexflow_tpu.search.machine_model import machine_model_for_mesh
    from flexflow_tpu.search.substitution import (
        base_optimize, evaluate_graph, generate_all_pcg_xfers,
    )
    from flexflow_tpu.search.unity import UnitySearch

    cm = CostModel(machine_model_for_mesh(mesh))

    # half 1: substitution-only (fixed degree-derived pricing)
    g1 = _pcg_of(ff)
    xfers = generate_all_pcg_xfers(mesh, config)
    _, subst_cost = base_optimize(g1, mesh, cm, xfers, budget=8,
                                  alpha=config.search_alpha)

    # half 2: placement DP only (no rewrites)
    g2 = _pcg_of(ff)
    us = UnitySearch(g2, mesh, config, cm)
    choice = us.run()
    t, mem = us.evaluate(choice)
    dp_cost = us._memory_penalized(t, mem)

    # joint
    g3 = _pcg_of(ff)
    best_g, best_choice, us3 = joint_graph_optimize(g3, mesh, config, cm)
    jt, jmem = us3.evaluate(best_choice)
    joint_cost = us3._memory_penalized(jt, jmem)

    # evaluators are shared, so the comparison is apples-to-apples
    assert joint_cost <= dp_cost * 1.0001
    assert joint_cost <= subst_cost * 1.0001


def test_joint_beats_both_halves_dlrm():
    """Same dominance property on the DLRM PCG (branchy: towers + MLPs)."""
    config = _config((2, 4, 1, 1), argv=["--budget", "6"])
    from flexflow_tpu import FFModel
    from flexflow_tpu.models import build_dlrm

    ff = FFModel(config)
    build_dlrm(ff, batch_size=config.batch_size)
    mesh = _mesh_for(config)

    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.joint import joint_graph_optimize
    from flexflow_tpu.search.machine_model import machine_model_for_mesh
    from flexflow_tpu.search.substitution import (
        base_optimize, generate_all_pcg_xfers,
    )
    from flexflow_tpu.search.unity import UnitySearch

    cm = CostModel(machine_model_for_mesh(mesh))

    g1 = _pcg_of(ff)
    xfers = generate_all_pcg_xfers(mesh, config)
    _, subst_cost = base_optimize(g1, mesh, cm, xfers, budget=6,
                                  alpha=config.search_alpha)

    g2 = _pcg_of(ff)
    us = UnitySearch(g2, mesh, config, cm)
    choice = us.run()
    t, mem = us.evaluate(choice)
    dp_cost = us._memory_penalized(t, mem)

    g3 = _pcg_of(ff)
    _, best_choice, us3 = joint_graph_optimize(g3, mesh, config, cm)
    jt, jmem = us3.evaluate(best_choice)
    joint_cost = us3._memory_penalized(jt, jmem)

    assert joint_cost <= dp_cost * 1.0001
    assert joint_cost <= subst_cost * 1.0001


def test_joint_search_bounded_on_bench_scale_lm():
    """Sequence splitting keeps the joint search's wall time bounded on a
    bench-scale LM (12 layers, ~100 nodes): reference
    generic_sequence_optimize, substitution.cc:2530+."""
    config = _config((2, 4, 1, 1), batch=8,
                     argv=["--budget", "6"])
    from flexflow_tpu import FFModel
    from flexflow_tpu.models import TransformerLMConfig, build_transformer_lm

    cfg = TransformerLMConfig(
        vocab_size=512, hidden_size=128, num_heads=4, num_layers=12,
        sequence_length=64, attention_impl="xla",
    )
    ff = FFModel(config)
    build_transformer_lm(ff, cfg, batch_size=8)
    g = _pcg_of(ff)
    mesh = _mesh_for(config)

    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.joint import joint_graph_optimize
    from flexflow_tpu.search.machine_model import machine_model_for_mesh

    cm = CostModel(machine_model_for_mesh(mesh))
    t0 = time.perf_counter()
    best_g, choice, us = joint_graph_optimize(g, mesh, config, cm)
    elapsed = time.perf_counter() - t0
    # generous CI bound; without sequence splitting + the shared segment
    # cache this takes many minutes
    assert elapsed < 120, f"joint search took {elapsed:.1f}s"
    assert best_g is not None and choice
    # repeated transformer blocks must hit the shared segment cache
    assert us.cache_hits > 0 or len(us._segment_cache) > 0


def test_joint_compile_trains():
    """FFModel.compile with search flags goes through the joint path and
    the resulting (possibly rewritten) model still learns."""
    from flexflow_tpu import (
        ActiMode, FFModel, LossType, MetricsType, SGDOptimizer,
    )

    config = _config((2, 4, 1, 1), batch=32,
                     argv=["--budget", "4", "--enable-parameter-parallel"])
    ff = FFModel(config)
    x = ff.create_tensor((32, 32))
    t = ff.dense(x, 64, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 64, ActiMode.AC_MODE_RELU, name="fc2")
    t = ff.softmax(ff.dense(t, 10, name="out"))
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])
    # searched placements came from the joint entry point
    assert ff._strategy is not None

    rs = np.random.RandomState(0)
    c = rs.randn(10, 32) * 3
    y = rs.randint(0, 10, 1024)
    xs = (c[y] + rs.randn(1024, 32)).astype(np.float32)
    ff.fit(xs, y.reshape(-1, 1).astype(np.int32), epochs=2)
    acc = ff.get_perf_metrics().get_accuracy()
    assert acc >= 0.85, acc
