"""Keras callbacks + datasets (reference keras/callbacks.py:1-90 and
keras/datasets/): LearningRateScheduler must measurably change the rate the
jitted step applies, VerifyMetrics/EpochVerifyMetrics gate and early-stop,
dataset loaders return real shapes/dtypes deterministically."""

import sys

import numpy as np
import pytest


def _mlp_model(batch=32):
    sys.argv = ["test", "-b", str(batch)]
    from flexflow_tpu.keras import Dense, Input, Model, SGD

    inp = Input(shape=(16,))
    # stable layer names: checkpoint leaf paths must match across fresh
    # model instances (the auto-naming counter is process-global)
    t = Dense(32, activation="relu", name="h")(inp)
    out = Dense(4, activation="softmax", name="out")(t)
    model = Model(inp, out)
    model.compile(optimizer=SGD(learning_rate=0.1),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    return model


def _toy_data(n=128, d=16, k=4):
    rs = np.random.RandomState(0)
    centers = rs.randn(k, d) * 3
    y = rs.randint(0, k, n)
    x = (centers[y] + rs.randn(n, d)).astype(np.float32)
    return x, y.reshape(-1, 1).astype(np.int32)


def _flat_params(ff):
    import jax

    return np.concatenate([
        np.asarray(jax.device_get(l)).ravel()
        for l in jax.tree.leaves(ff._params)])


def test_scheduler_changes_effective_lr():
    """schedule -> 0.0 must freeze the parameters: proves the new rate
    reaches the COMPILED step (executable invalidated + rebuilt), not just
    a Python attribute."""
    from flexflow_tpu.keras import LearningRateScheduler

    model = _mlp_model()
    x, y = _toy_data()
    before = _flat_params(model.ffmodel)
    model.fit(x, y, epochs=1,
              callbacks=[LearningRateScheduler(lambda e: 0.0)])
    assert model.optimizer.lr == 0.0
    after = _flat_params(model.ffmodel)
    np.testing.assert_array_equal(before, after)

    # and a real rate trains: params move and the schedule's value sticks
    model.fit(x, y, epochs=2,
              callbacks=[LearningRateScheduler(
                  lambda e: 0.2 if e == 0 else 0.05)])
    assert model.optimizer.lr == 0.05
    assert not np.array_equal(after, _flat_params(model.ffmodel))


def test_scheduler_rejects_non_float():
    from flexflow_tpu.keras import LearningRateScheduler

    model = _mlp_model()
    x, y = _toy_data()
    with pytest.raises(ValueError, match="should be float"):
        model.fit(x, y, epochs=1,
                  callbacks=[LearningRateScheduler(lambda e: "fast")])


def test_verify_metrics_gate():
    from flexflow_tpu.keras import VerifyMetrics

    model = _mlp_model()
    x, y = _toy_data(n=256)
    model.fit(x, y, epochs=3, callbacks=[VerifyMetrics(0.5)])  # passes
    with pytest.raises(AssertionError, match="accuracy gate"):
        model.fit(x, y, epochs=1, callbacks=[VerifyMetrics(1.01)])


def test_epoch_verify_early_stop():
    """EpochVerifyMetrics returning True stops training: with gate 0.0 the
    loop runs exactly one epoch even when 10 are requested."""
    from flexflow_tpu.keras import Callback, EpochVerifyMetrics

    class EpochCounter(Callback):
        def __init__(self):
            super().__init__()
            self.n = 0

        def on_epoch_begin(self, epoch, logs=None):
            self.n += 1

    model = _mlp_model()
    x, y = _toy_data()
    counter = EpochCounter()
    model.fit(x, y, epochs=10,
              callbacks=[counter, EpochVerifyMetrics(0.0)])
    assert counter.n == 1


def test_model_checkpoint_periodic_saves(tmp_path):
    """ModelCheckpoint (resilience-backed) commits one checkpoint per epoch
    by default; the checkpoints are discoverable and restorable."""
    from flexflow_tpu.keras import ModelCheckpoint
    from flexflow_tpu.resilience import latest_checkpoint, list_checkpoints

    model = _mlp_model()
    x, y = _toy_data()
    root = str(tmp_path / "ck")
    cb = ModelCheckpoint(root, keep=5)
    model.fit(x, y, epochs=3, callbacks=[cb])
    ckpts = list_checkpoints(root)
    assert len(ckpts) == 3  # one per epoch, all committed
    assert cb.last_saved is not None
    # restorable into a fresh model (this is the save-best/resume path)
    model2 = _mlp_model()
    model2.ffmodel.load_checkpoint(root)
    np.testing.assert_allclose(_flat_params(model2.ffmodel),
                               _flat_params(model.ffmodel), rtol=1e-6)
    assert latest_checkpoint(root) == ckpts[-1]


def test_model_checkpoint_save_best_only(tmp_path):
    """save_best_only skips epochs that don't improve the monitored metric;
    `best` tracks the high-water mark."""
    from flexflow_tpu.keras import ModelCheckpoint
    from flexflow_tpu.resilience import list_checkpoints

    model = _mlp_model()
    x, y = _toy_data(n=256)
    root = str(tmp_path / "ck")
    cb = ModelCheckpoint(root, monitor="accuracy", save_best_only=True)

    # monkeypatch the metric stream: improves, regresses, improves
    vals = iter([0.5, 0.3, 0.7])
    cb._metric = lambda: next(vals)
    model.fit(x, y, epochs=3, callbacks=[cb])
    assert cb.best == 0.7
    assert len(list_checkpoints(root)) == 2  # epochs 0 and 2 only


def test_model_checkpoint_every_n_epochs_and_validation(tmp_path):
    from flexflow_tpu.keras import ModelCheckpoint
    from flexflow_tpu.resilience import list_checkpoints

    with pytest.raises(ValueError, match="monitor"):
        ModelCheckpoint(str(tmp_path), monitor="f1")
    with pytest.raises(ValueError, match="every_n_epochs"):
        ModelCheckpoint(str(tmp_path), every_n_epochs=0)

    model = _mlp_model()
    x, y = _toy_data()
    root = str(tmp_path / "ck")
    model.fit(x, y, epochs=4,
              callbacks=[ModelCheckpoint(root, every_n_epochs=2)])
    assert len(list_checkpoints(root)) == 2  # epochs 1 and 3


def test_model_checkpoint_never_stops_training(tmp_path):
    """on_epoch_end returning truthy stops fit (the early-stop contract) —
    ModelCheckpoint must never trigger it."""
    from flexflow_tpu.keras import Callback, ModelCheckpoint

    class EpochCounter(Callback):
        def __init__(self):
            super().__init__()
            self.n = 0

        def on_epoch_begin(self, epoch, logs=None):
            self.n += 1

    model = _mlp_model()
    x, y = _toy_data()
    counter = EpochCounter()
    model.fit(x, y, epochs=3,
              callbacks=[ModelCheckpoint(str(tmp_path / "ck")), counter])
    assert counter.n == 3


def test_mnist_loader_shapes_and_determinism():
    from flexflow_tpu.keras.datasets import mnist

    (xtr, ytr), (xte, yte) = mnist.load_data(n_train=512, n_test=64)
    assert xtr.shape == (512, 28, 28) and xtr.dtype == np.uint8
    assert ytr.shape == (512,) and ytr.dtype == np.uint8
    assert xte.shape == (64, 28, 28) and yte.shape == (64,)
    (xtr2, _), _ = mnist.load_data(n_train=512, n_test=64)
    np.testing.assert_array_equal(xtr, xtr2)
    with pytest.raises(FileNotFoundError):
        mnist.load_data(path="definitely_absent.npz", synthetic=False)


def test_cifar10_loader_shapes():
    from flexflow_tpu.keras.datasets import cifar10

    (xtr, ytr), (xte, yte) = cifar10.load_data(n_train=256, n_test=32)
    assert xtr.shape == (256, 3, 32, 32) and xtr.dtype == np.uint8
    assert ytr.shape == (256, 1)
    assert xte.shape == (32, 3, 32, 32) and yte.shape == (32, 1)


def test_mnist_synthetic_is_learnable():
    """The synthetic fallback must be separable enough that the reference
    examples' >=90% gates are meaningful."""
    from flexflow_tpu.keras import Dense, Input, Model, SGD, VerifyMetrics
    from flexflow_tpu.keras.datasets import mnist

    sys.argv = ["test", "-b", "64"]
    (x_train, y_train), _ = mnist.load_data(n_train=2048, n_test=64)
    x = x_train.reshape(-1, 784).astype(np.float32) / 255.0
    y = y_train.reshape(-1, 1).astype(np.int32)

    inp = Input(shape=(784,))
    out = Dense(10, activation="softmax")(Dense(64, activation="relu")(inp))
    model = Model(inp, out)
    model.compile(optimizer=SGD(learning_rate=0.05),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    # fit() shuffles via the global numpy RNG; pin it so the cumulative
    # accuracy (counters accumulate across epochs) is order-independent
    np.random.seed(0)
    model.fit(x, y, epochs=5, callbacks=[VerifyMetrics(0.90)])
