"""Pallas kernel tests (interpret mode on the CPU test backend)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(causal):
    from flexflow_tpu.kernels.flash_attention import (
        _attn_reference,
        flash_attention,
    )

    rs = np.random.RandomState(0)
    b, h, s, d = 2, 2, 256, 32
    q = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)

    expected = _attn_reference(q, k, v, causal, scale)
    got = flash_attention(q, k, v, causal=causal, scale=scale,
                          block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_grad():
    from flexflow_tpu.kernels.flash_attention import (
        _attn_reference,
        flash_attention,
    )

    rs = np.random.RandomState(1)
    b, h, s, d = 1, 2, 128, 16
    q = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=64, block_k=64) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_attn_reference(q, k, v, True, 1.0 / np.sqrt(d)) ** 2)

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)


def test_flash_attention_small_shape_fallback():
    from flexflow_tpu.kernels.flash_attention import flash_attention

    q = jnp.ones((1, 1, 8, 4))
    out = flash_attention(q, q, q, causal=False)
    assert out.shape == (1, 1, 8, 4)


def test_flash_attention_ragged_seq():
    """seq_k not divisible by block_k: padded tail must be masked."""
    from flexflow_tpu.kernels.flash_attention import (
        _attn_reference,
        flash_attention,
    )

    rs = np.random.RandomState(2)
    b, h, s, d = 1, 1, 320, 16
    q = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)
    for causal in (False, True):
        expected = _attn_reference(q, k, v, causal, scale)
        got = flash_attention(q, k, v, causal=causal, scale=scale,
                              block_q=128, block_k=128)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=2e-5, atol=2e-5)


def test_flash_attention_cross_causal_alignment():
    """s_q != s_k causal: mask must be bottom-right aligned like sdpa_xla."""
    from flexflow_tpu.kernels.flash_attention import (
        _attn_reference,
        flash_attention,
    )

    rs = np.random.RandomState(3)
    b, h, d = 1, 2, 16
    q = jnp.asarray(rs.randn(b, h, 128, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, 256, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, 256, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)
    expected = _attn_reference(q, k, v, True, scale)
    got = flash_attention(q, k, v, causal=True, scale=scale,
                          block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
# (128, 128) with block 128 exercises the fused single-tile backward
# (ni == nj == 1 — the benchmark's own seq==block configuration)
@pytest.mark.parametrize(
    "sq,sk", [(256, 256), (320, 320), (128, 256), (320, 192), (128, 128)])
def test_flash_backward_matches_reference(causal, sq, sk):
    """Pallas dq/dk/dv kernels vs XLA autodiff of the reference attention,
    including ragged and cross-length causal shapes."""
    from flexflow_tpu.kernels.flash_attention import (
        _attn_reference,
        flash_attention,
    )

    if causal and sk < sq:
        pytest.skip("bottom-right causal with sk<sq leaves rows keyless")
    rs = np.random.RandomState(4)
    b, h, d = 2, 2, 16
    q = jnp.asarray(rs.randn(b, h, sq, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, sk, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, sk, d), jnp.float32)
    ct = jnp.asarray(rs.randn(b, h, sq, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)

    _, vjp_flash = jax.vjp(
        lambda q_, k_, v_: flash_attention(
            q_, k_, v_, causal=causal, scale=scale, block_q=128, block_k=128
        ), q, k, v,
    )
    _, vjp_ref = jax.vjp(
        lambda q_, k_, v_: _attn_reference(q_, k_, v_, causal, scale), q, k, v
    )
    for got, want, name in zip(vjp_flash(ct), vjp_ref(ct), "qkv"):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4,
            err_msg=f"d{name} mismatch sq={sq} sk={sk} causal={causal}",
        )


def test_flash_backward_bf16():
    """bf16 inputs: backward runs in the kernel path and tracks the fp32
    reference to bf16 tolerance."""
    from flexflow_tpu.kernels.flash_attention import (
        _attn_reference,
        flash_attention,
    )

    rs = np.random.RandomState(5)
    b, h, s, d = 1, 2, 256, 32
    qf = rs.randn(b, h, s, d).astype(np.float32)
    kf = rs.randn(b, h, s, d).astype(np.float32)
    vf = rs.randn(b, h, s, d).astype(np.float32)
    scale = 1.0 / np.sqrt(d)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, scale=scale,
                            block_q=128, block_k=128).astype(jnp.float32) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(
            _attn_reference(q, k, v, True, scale).astype(jnp.float32) ** 2
        )

    g_bf16 = jax.grad(loss_flash, argnums=(0, 1, 2))(
        jnp.asarray(qf, jnp.bfloat16), jnp.asarray(kf, jnp.bfloat16),
        jnp.asarray(vf, jnp.bfloat16),
    )
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(
        jnp.asarray(qf), jnp.asarray(kf), jnp.asarray(vf)
    )
    for a, b_ in zip(g_bf16, g_ref):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b_), rtol=0.1, atol=0.5
        )


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_fused_layer_norm_matches_reference(dtype):
    """kernels/layer_norm.py fwd + bwd vs the jnp reference formula."""
    import jax
    import jax.numpy as jnp

    from flexflow_tpu.kernels.layer_norm import fused_layer_norm_or_none

    rs = np.random.RandomState(0)
    n, d = 512, 256
    x = jnp.asarray(rs.randn(2, n // 2, d), dtype)
    scale = jnp.asarray(rs.randn(d) * 0.5 + 1.0, jnp.float32)
    bias = jnp.asarray(rs.randn(d) * 0.1, jnp.float32)
    eps = 1e-5

    def ref(x, scale, bias):
        xf = x.astype(jnp.float32)
        mu = xf.mean(-1, keepdims=True)
        var = xf.var(-1, keepdims=True)
        y = (xf - mu) * jax.lax.rsqrt(var + eps) * scale + bias
        return y.astype(x.dtype)

    def fused(x, scale, bias):
        out = fused_layer_norm_or_none(x, scale, bias, (-1,), eps)
        assert out is not None
        return out

    tol = dict(rtol=1e-5, atol=1e-5) if dtype == "float32" else dict(
        rtol=2e-2, atol=2e-2)
    y_f = jax.jit(fused)(x, scale, bias)
    y_r = jax.jit(ref)(x, scale, bias)
    np.testing.assert_allclose(np.asarray(y_f, np.float32),
                               np.asarray(y_r, np.float32), **tol)

    g = jnp.asarray(rs.randn(2, n // 2, d), dtype)

    def loss(f):
        def inner(x, scale, bias):
            return jnp.sum(f(x, scale, bias).astype(jnp.float32)
                           * g.astype(jnp.float32))
        return inner

    gf = jax.jit(jax.grad(loss(fused), argnums=(0, 1, 2)))(x, scale, bias)
    gr = jax.jit(jax.grad(loss(ref), argnums=(0, 1, 2)))(x, scale, bias)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), **tol)


def test_fused_layer_norm_gates_to_fallback():
    """Ragged / non-last-axis shapes return None (jnp fallback)."""
    import jax.numpy as jnp

    from flexflow_tpu.kernels.layer_norm import fused_layer_norm_or_none

    x = jnp.zeros((8, 100))  # d % 128 != 0
    s = jnp.ones((100,)); b = jnp.zeros((100,))
    assert fused_layer_norm_or_none(x, s, b, (-1,), 1e-5) is None
    x2 = jnp.zeros((8, 16, 128))
    s2 = jnp.ones((16,)); b2 = jnp.zeros((16,))
    assert fused_layer_norm_or_none(x2, s2, b2, (1,), 1e-5) is None


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s,block", [(256, 128), (128, 128)])
def test_flash_packed_matches_reference(causal, s, block):
    """(b, s, h·d) packed layout (head selection via lane-offset index
    maps): forward must match the transposed-layout reference on both the
    online-softmax (s > block) and one-pass (s == block) paths."""
    from flexflow_tpu.kernels.flash_attention import (
        _attn_reference,
        flash_attention_packed,
    )

    rs = np.random.RandomState(0)
    b, h, d = 2, 4, 32
    qp = jnp.asarray(rs.randn(b, s, h * d), jnp.float32)
    kp = jnp.asarray(rs.randn(b, s, h * d), jnp.float32)
    vp = jnp.asarray(rs.randn(b, s, h * d), jnp.float32)
    scale = 1.0 / np.sqrt(d)

    def split(t):
        return t.reshape(b, s, h, d).transpose(0, 2, 1, 3)

    expected = _attn_reference(split(qp), split(kp), split(vp), causal,
                               scale)
    expected = expected.transpose(0, 2, 1, 3).reshape(b, s, h * d)
    got = flash_attention_packed(qp, kp, vp, num_heads=h, causal=causal,
                                 scale=scale, block_q=block, block_k=block)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("s,block", [(256, 128), (128, 128)])
def test_flash_packed_grad(s, block):
    """Packed-layout backward (single-tile fused and split dq/dkv paths)
    against the XLA reference."""
    from flexflow_tpu.kernels.flash_attention import (
        _attn_reference,
        flash_attention_packed,
    )

    rs = np.random.RandomState(1)
    b, h, d = 1, 2, 16
    qp = jnp.asarray(rs.randn(b, s, h * d), jnp.float32)
    kp = jnp.asarray(rs.randn(b, s, h * d), jnp.float32)
    vp = jnp.asarray(rs.randn(b, s, h * d), jnp.float32)

    def f_packed(q, k, v):
        return jnp.sum(flash_attention_packed(
            q, k, v, num_heads=h, causal=True,
            block_q=block, block_k=block) ** 2)

    def f_ref(q, k, v):
        def split(t):
            return t.reshape(b, s, h, d).transpose(0, 2, 1, 3)

        o = _attn_reference(split(q), split(k), split(v), True,
                            1.0 / np.sqrt(d))
        return jnp.sum(o ** 2)

    g1 = jax.grad(f_packed, argnums=(0, 1, 2))(qp, kp, vp)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(qp, kp, vp)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)
