"""Pallas kernel tests (interpret mode on the CPU test backend)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(causal):
    from flexflow_tpu.kernels.flash_attention import (
        _attn_reference,
        flash_attention,
    )

    rs = np.random.RandomState(0)
    b, h, s, d = 2, 2, 256, 32
    q = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)

    expected = _attn_reference(q, k, v, causal, scale)
    got = flash_attention(q, k, v, causal=causal, scale=scale,
                          block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_grad():
    from flexflow_tpu.kernels.flash_attention import (
        _attn_reference,
        flash_attention,
    )

    rs = np.random.RandomState(1)
    b, h, s, d = 1, 2, 128, 16
    q = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True,
                                       block_q=64, block_k=64) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_attn_reference(q, k, v, True, 1.0 / np.sqrt(d)) ** 2)

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)


def test_flash_attention_small_shape_fallback():
    from flexflow_tpu.kernels.flash_attention import flash_attention

    q = jnp.ones((1, 1, 8, 4))
    out = flash_attention(q, q, q, causal=False)
    assert out.shape == (1, 1, 8, 4)


def test_flash_attention_ragged_seq():
    """seq_k not divisible by block_k: padded tail must be masked."""
    from flexflow_tpu.kernels.flash_attention import (
        _attn_reference,
        flash_attention,
    )

    rs = np.random.RandomState(2)
    b, h, s, d = 1, 1, 320, 16
    q = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)
    for causal in (False, True):
        expected = _attn_reference(q, k, v, causal, scale)
        got = flash_attention(q, k, v, causal=causal, scale=scale,
                              block_q=128, block_k=128)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=2e-5, atol=2e-5)


def test_flash_attention_cross_causal_alignment():
    """s_q != s_k causal: mask must be bottom-right aligned like sdpa_xla."""
    from flexflow_tpu.kernels.flash_attention import (
        _attn_reference,
        flash_attention,
    )

    rs = np.random.RandomState(3)
    b, h, d = 1, 2, 16
    q = jnp.asarray(rs.randn(b, h, 128, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, 256, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, 256, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)
    expected = _attn_reference(q, k, v, True, scale)
    got = flash_attention(q, k, v, causal=True, scale=scale,
                          block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)
