"""Long-context leg (SURVEY §5 — capability the reference lacks): ring
attention over the seq axis and the flash kernel's online-softmax path must
agree with the XLA reference at 4k sequence on the CPU mesh. The real-chip
throughput leg is bench.py's seq-4096 secondary metric.

Round 7 widens this into the long-context roofline matrix: the
double-buffered flash-block ring (forward AND gradient, causal and
bidirectional, 2- and 4-shard seq axes, non-divisible s_loc, overlap
on/off), the relayout-free narrow-head packed kernels, and the decomposed
collective matmul — all on the CPU `shard_map` mesh so tier-1 exercises
the exact schedules the TPU runs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh


def _mesh_seq8():
    devs = np.array(jax.devices()[:8]).reshape(1, 1, 8)
    return Mesh(devs, ("data", "model", "seq"))


def _mesh_seq(n):
    from flexflow_tpu.machine import MeshShape, build_mesh

    return build_mesh(MeshShape((1, 1, n, 1),
                                ("data", "model", "seq", "pipe")))


def test_ring_vs_flash_vs_reference_seq4k():
    from flexflow_tpu.kernels.flash_attention import (
        _attn_reference,
        flash_attention,
    )
    from flexflow_tpu.parallel.ring_attention import ring_attention

    rs = np.random.RandomState(0)
    b, h, s, d = 1, 1, 4096, 8
    q = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)

    ref = np.asarray(_attn_reference(q, k, v, True, scale))
    flash = np.asarray(flash_attention(q, k, v, causal=True, scale=scale,
                                       block_q=512, block_k=512))
    np.testing.assert_allclose(flash, ref, rtol=2e-4, atol=2e-4)

    mesh = _mesh_seq8()
    ring = np.asarray(jax.jit(
        lambda q, k, v: ring_attention(q, k, v, causal=True, scale=scale,
                                       mesh=mesh)
    )(q, k, v))
    np.testing.assert_allclose(ring, ref, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("n_shards", [2, 4])
@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("overlap", [True, False])
def test_ring_forward_and_grad_parity(n_shards, causal, overlap):
    """Ring attention (flash-block body, causal skip, double-buffered
    hops) vs the dense reference: forward and gradients, on a seq axis of
    2 and 4 shards with a NON-divisible-by-anything-clean s_loc (s=24·n →
    s_loc=24: not a lane multiple, not a power of two)."""
    from flexflow_tpu.ops.attention import sdpa_xla
    from flexflow_tpu.parallel.ring_attention import ring_attention

    mesh = _mesh_seq(n_shards)
    rs = np.random.RandomState(n_shards)
    b, h, d = 2, 2, 8
    s = 24 * n_shards
    q = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)

    def ring(q, k, v):
        return ring_attention(q, k, v, causal=causal, scale=scale,
                              mesh=mesh, overlap=overlap)

    expected = np.asarray(sdpa_xla(q, k, v, causal=causal, scale=scale))
    got = np.asarray(jax.jit(ring)(q, k, v))
    np.testing.assert_allclose(got, expected, rtol=2e-5, atol=2e-5)

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(sdpa_xla(q, k, v, causal=causal, scale=scale) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)


def test_ring_flash_block_path_seq512():
    """s_loc = 128 clears the flash kernel's shape gate, so the per-block
    attention runs the REAL Pallas online-softmax kernel (interpret mode
    on CPU) inside shard_map — forward and gradient vs the dense
    reference."""
    from flexflow_tpu.ops.attention import sdpa_xla
    from flexflow_tpu.parallel.ring_attention import ring_attention

    mesh = _mesh_seq(4)
    rs = np.random.RandomState(7)
    b, h, s, d = 1, 1, 512, 8
    q = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)

    def ring(q, k, v):
        return ring_attention(q, k, v, causal=True, scale=scale, mesh=mesh)

    expected = np.asarray(sdpa_xla(q, k, v, causal=True, scale=scale))
    got = np.asarray(jax.jit(ring)(q, k, v))
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-4)

    g = jax.jit(jax.grad(lambda q, k, v: jnp.sum(ring(q, k, v) ** 2),
                         argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(
            sdpa_xla(q, k, v, causal=True, scale=scale) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("shape,causal,blocks", [
    ((1, 128, 2, 64), True, (512, 512)),   # hpb=2, single kv tile
    ((1, 256, 2, 64), True, (128, 128)),   # hpb=2, online-softmax path
    ((2, 128, 4, 32), False, (512, 512)),  # hpb=4
    ((1, 128, 3, 40), True, (512, 512)),   # 128 % 40 != 0 → full-width
    ((1, 200, 2, 64), True, (128, 128)),   # ragged kv tail
])
def test_narrow_head_packed_kernel_parity(shape, causal, blocks):
    """The grouped narrow-head packed path (head_dim < 128: head-GROUP
    lane blocks + in-kernel static head loop) vs the transposed-layout
    kernels, forward AND backward, in interpret mode — the relayout-free
    path the flagship's head_dim-64 model now takes."""
    from flexflow_tpu.kernels.flash_attention import (
        _packed_heads_per_block,
        flash_attention,
        flash_attention_packed,
    )

    b, s, h, d = shape
    bq, bk = blocks
    assert _packed_heads_per_block(d, h) > 1  # the grouped path, not 1-head
    e = h * d
    rs = np.random.RandomState(d)
    q = jnp.asarray(rs.randn(b, s, e), jnp.float32)
    k = jnp.asarray(rs.randn(b, s, e), jnp.float32)
    v = jnp.asarray(rs.randn(b, s, e), jnp.float32)

    def packed(q, k, v):
        return flash_attention_packed(q, k, v, num_heads=h, causal=causal,
                                      block_q=bq, block_k=bk)

    def ref(q, k, v):
        def split(t):
            return t.reshape(b, s, h, d).transpose(0, 2, 1, 3)

        out = flash_attention(split(q), split(k), split(v), causal=causal,
                              block_q=bq, block_k=bk)
        return out.transpose(0, 2, 1, 3).reshape(b, s, e)

    np.testing.assert_allclose(np.asarray(packed(q, k, v)),
                               np.asarray(ref(q, k, v)),
                               rtol=2e-4, atol=2e-4)
    g_p = jax.grad(lambda *a: jnp.sum(packed(*a) ** 2),
                   argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(lambda *a: jnp.sum(ref(*a) ** 2),
                   argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_p, g_r):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=3e-4, atol=3e-4)


@pytest.mark.parametrize("overlap", [True, False])
def test_allgather_matmul_parity(overlap):
    """Decomposed all_gather→matmul (parallel/ops.allgather_matmul): the
    overlapped block-rotation schedule must equal the gathered matmul,
    values and gradients."""
    from flexflow_tpu.machine import MeshShape, build_mesh
    from flexflow_tpu.parallel.ops import allgather_matmul

    mesh = build_mesh(MeshShape((2, 4, 1, 1),
                                ("data", "model", "seq", "pipe")))
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(8, 16, 64), jnp.float32)
    w = jnp.asarray(rs.randn(64, 32), jnp.float32)
    ref = np.asarray(jnp.dot(x, w))
    got = np.asarray(jax.jit(lambda x, w: allgather_matmul(
        x, w, mesh=mesh, overlap=overlap))(x, w))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    g = jax.jit(jax.grad(lambda x, w: jnp.sum(allgather_matmul(
        x, w, mesh=mesh, overlap=overlap) ** 2), argnums=(0, 1)))(x, w)
    g_ref = jax.grad(lambda x, w: jnp.sum(jnp.dot(x, w) ** 2),
                     argnums=(0, 1))(x, w)
    for a, b_ in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)


def test_ablation_flags_reach_the_op(monkeypatch):
    """`--no-overlap-collectives` / `--flash-transposed` must flip the
    COMPILED schedule, not just the cost model's pricing: the flags flow
    FFConfig → OpContext → the attention op's kernel/ring dispatch.
    Captured at the op seam so the test is cheap and pins the plumbing."""
    from flexflow_tpu.executor import OpContext
    from flexflow_tpu.ops import attention as attn_mod
    from flexflow_tpu.ops.attention import (
        MultiHeadAttentionParams, _mha_forward,
    )

    seen = {}

    def fake_ring(q, k, v, *, causal, scale, mesh, overlap):
        seen["ring_overlap"] = overlap
        return jnp.zeros_like(q)

    def fake_packed(q, k, v, *, num_heads, causal, scale):
        seen["layout"] = "packed"
        return jnp.zeros_like(q)

    def fake_transposed(q, k, v, *, causal, scale):
        seen["layout"] = "transposed"
        return jnp.zeros_like(q)

    # importlib: the kernels package re-exports `flash_attention` the
    # function, which shadows the submodule on attribute-style imports
    import importlib

    fa = importlib.import_module("flexflow_tpu.kernels.flash_attention")
    ra = importlib.import_module("flexflow_tpu.parallel.ring_attention")

    monkeypatch.setattr(ra, "ring_attention", fake_ring)
    monkeypatch.setattr(fa, "flash_attention_packed", fake_packed)
    monkeypatch.setattr(fa, "flash_attention", fake_transposed)
    assert attn_mod  # the op imports the seams at call time

    E, H = 16, 2
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(2, 8, E), jnp.float32)
    w = {n: jnp.asarray(rs.randn(E, E), jnp.float32)
         for n in ("wq", "wk", "wv", "wo")}
    w.update({n: jnp.zeros((E,), jnp.float32)
              for n in ("bq", "bk", "bv", "bo")})

    for impl, ctx_kw, expect in (
        ("ring", {"overlap_collectives": False}, ("ring_overlap", False)),
        ("ring", {"overlap_collectives": True}, ("ring_overlap", True)),
        ("flash", {"flash_packed": True}, ("layout", "packed")),
        ("flash", {"flash_packed": False}, ("layout", "transposed")),
    ):
        seen.clear()
        p = MultiHeadAttentionParams(embed_dim=E, num_heads=H, impl=impl)
        _mha_forward(p, (x, x, x), w, None, OpContext(**ctx_kw))
        key, val = expect
        assert seen.get(key) == val, (impl, ctx_kw, seen)

    # and the FFConfig flags parse into the fields the executor forwards
    from flexflow_tpu import FFConfig

    c = FFConfig()
    c.parse_args(["--no-overlap-collectives", "--flash-transposed"])
    assert c.overlap_collectives is False
    assert c.flash_packed_layout is False
