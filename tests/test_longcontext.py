"""Long-context leg (SURVEY §5 — capability the reference lacks): ring
attention over the seq axis and the flash kernel's online-softmax path must
agree with the XLA reference at 4k sequence on the CPU mesh. The real-chip
throughput leg is bench.py's seq-4096 secondary metric."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh


def _mesh_seq8():
    devs = np.array(jax.devices()[:8]).reshape(1, 1, 8)
    return Mesh(devs, ("data", "model", "seq"))


def test_ring_vs_flash_vs_reference_seq4k():
    from flexflow_tpu.kernels.flash_attention import (
        _attn_reference,
        flash_attention,
    )
    from flexflow_tpu.parallel.ring_attention import ring_attention

    rs = np.random.RandomState(0)
    b, h, s, d = 1, 1, 4096, 8
    q = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    scale = 1.0 / np.sqrt(d)

    ref = np.asarray(_attn_reference(q, k, v, True, scale))
    flash = np.asarray(flash_attention(q, k, v, causal=True, scale=scale,
                                       block_q=512, block_k=512))
    np.testing.assert_allclose(flash, ref, rtol=2e-4, atol=2e-4)

    mesh = _mesh_seq8()
    ring = np.asarray(jax.jit(
        lambda q, k, v: ring_attention(q, k, v, causal=True, scale=scale,
                                       mesh=mesh)
    )(q, k, v))
    np.testing.assert_allclose(ring, ref, rtol=2e-4, atol=2e-4)
