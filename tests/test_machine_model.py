"""Topology-aware machine model (TorusMachineModel): wraparound vs open
axes, multi-hop all_to_all routing, ring-rotation wrap-edge pricing, DCN
NIC fan-in, and file loading — the NetworkedMachineModel/
EnhancedMachineModel analog (reference simulator.h:212-615,
network.cc:1-586, machine_model.cc:1-1287) recast to torus closed forms."""

import json

import pytest

from flexflow_tpu.search.machine_model import (
    CHIPS,
    AxisTopology,
    TorusMachineModel,
    machine_model_for_mesh,
    machine_model_from_file,
)


def _model(sizes, topology, chips_per_host=1):
    return TorusMachineModel(CHIPS["v5e"], dict(sizes),
                             topology=topology,
                             axis_over_dcn=frozenset(
                                 a for a, t in topology.items() if t.over_dcn),
                             chips_per_host=chips_per_host)


def test_wraparound_ring_beats_open_line():
    # the VERDICT acceptance case: same bytes, same axis size — the wrapped
    # axis runs the bidirectional ring, the open one cannot
    wrapped = _model({"data": 8}, {"data": AxisTopology(wraparound=True)})
    open_ = _model({"data": 8}, {"data": AxisTopology(wraparound=False)})
    b = 1e8
    assert wrapped.all_gather(b, "data") < open_.all_gather(b, "data")
    assert wrapped.all_reduce(b, "data") < open_.all_reduce(b, "data")
    # exactly the 2× ring-direction factor (latency terms are equal)
    lat = 7 * wrapped._lat("data")
    assert wrapped.all_gather(b, "data") - lat == pytest.approx(
        (open_.all_gather(b, "data") - lat) / 2)


def test_all_to_all_routing_torus_vs_line():
    # mean hop distance n/4 (ring) vs ~n/3 (line) over fewer link-dirs:
    # the open axis pays ~1.5× at n=8
    wrapped = _model({"x": 8}, {"x": AxisTopology(wraparound=True)})
    open_ = _model({"x": 8}, {"x": AxisTopology(wraparound=False)})
    b = 1e8
    t_w = wrapped.all_to_all(b, "x")
    t_o = open_.all_to_all(b, "x")
    assert t_w < t_o
    assert t_o / t_w == pytest.approx(1.5, rel=0.05)


def test_rotate_wrap_edge_serializes_on_open_axis():
    # ring attention's K/V rotation: 1 hop on a torus, a full line
    # traversal on an open axis (the wrap pair crosses all n−1 links)
    n = 8
    wrapped = _model({"seq": n}, {"seq": AxisTopology(wraparound=True)})
    open_ = _model({"seq": n}, {"seq": AxisTopology(wraparound=False)})
    b = 1e7
    assert open_.rotate(b, "seq") == pytest.approx(
        (n - 1) * wrapped.rotate(b, "seq"))
    # the pipeline hand-off (ppermute, no wrap edge) is topology-blind
    assert open_.ppermute(b, "seq") == wrapped.ppermute(b, "seq")


def test_dcn_fan_in_shares_the_nic():
    topo = {"dcn": AxisTopology(over_dcn=True, wraparound=False)}
    alone = _model({"dcn": 4}, topo, chips_per_host=1)
    shared = _model({"dcn": 4}, topo, chips_per_host=4)
    b = 1e8
    lat = 3 * alone._lat("dcn")
    assert (shared.all_gather(b, "dcn") - lat) == pytest.approx(
        4 * (alone.all_gather(b, "dcn") - lat))


def test_links_multiply_bandwidth():
    one = _model({"m": 4}, {"m": AxisTopology(links=1)})
    two = _model({"m": 4}, {"m": AxisTopology(links=2)})
    b = 1e8
    lat = 3 * one._lat("m")
    assert (two.all_gather(b, "m") - lat) == pytest.approx(
        (one.all_gather(b, "m") - lat) / 2)


def test_for_mesh_defaults_wrap_ici_not_dcn():
    m = machine_model_for_mesh({"dcn": 2, "data": 4}, chip=CHIPS["v5e"],
                               num_hosts=2)
    assert isinstance(m, TorusMachineModel)
    assert m._topo("data").wraparound
    assert m._topo("dcn").over_dcn and not m._topo("dcn").wraparound
    assert m.chips_per_host == 4  # 8 chips over 2 hosts


def test_file_topology_roundtrip(tmp_path):
    p = tmp_path / "mm.json"
    p.write_text(json.dumps({
        "chip": "v5e",
        "topology": {"data": {"wraparound": False, "links": 2}},
        "chips_per_host": 4,
        "dcn_axes": ["dcn"],
    }))
    m = machine_model_from_file(str(p), {"dcn": 2, "data": 8, "model": 1})
    assert isinstance(m, TorusMachineModel)
    t = m._topo("data")
    assert not t.wraparound and t.links == 2
    assert m.chips_per_host == 4
    # DCN all_gather reflects the fan-in derating
    b = 1e8
    n = 2
    expect = (n - 1) / n * b / (m.chip.dcn_bandwidth / 4) + (n - 1) * m._lat("dcn")
    assert m.all_gather(b, "dcn") == pytest.approx(expect)


def test_file_topology_unknown_axis_rejected(tmp_path):
    p = tmp_path / "mm.json"
    p.write_text(json.dumps({"chip": "v5e",
                             "topology": {"tyop": {"wraparound": False}}}))
    with pytest.raises(ValueError, match="topology axes"):
        machine_model_from_file(str(p), {"data": 8})


def test_search_output_changes_with_topology(monkeypatch):
    """The VERDICT acceptance: the search's decision flips with the axis
    topology on the same mesh. A Linear with in=2048, out=4096, batch=1024
    on an 8-wide model axis: tp_row saves 7/8 of the compute but pays a
    ring all_reduce of the full output (~16.8 MB). On a wrapped axis the
    bidirectional ring prices that psum below the compute savings (tp_row
    wins); on an open axis it prices above them (dp wins)."""
    import sys

    monkeypatch.setattr(sys, "argv",
                        ["test", "--enable-parameter-parallel",
                         "--budget", "0"])
    from test_joint_search import _pcg_of

    from flexflow_tpu import ActiMode, FFConfig, FFModel
    from flexflow_tpu.machine import build_mesh
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.unity import UnitySearch

    config = FFConfig()
    config.mesh_axis_sizes = (1, 8, 1, 1)
    config.batch_size = 1024
    ff = FFModel(config)
    x = ff.create_tensor((1024, 2048), name="x")
    ff.dense(x, 4096, ActiMode.AC_MODE_NONE, name="fc")
    mesh = build_mesh(config.mesh_shape())

    sizes = dict(mesh.shape)

    def best_name(mm):
        g = _pcg_of(ff)
        us = UnitySearch(g, mesh, config, CostModel(mm))
        fc = next(n for n in g.topo_order() if n.name == "fc")
        costs = {}
        for cfg in us.node_configs(fc):
            t, _ = us.evaluate({fc.guid: cfg})
            costs[cfg.name] = t
        assert {"dp", "tp_row"} <= set(costs)
        return min(costs, key=costs.get), costs

    wrapped = _model(sizes, {a: AxisTopology(wraparound=True)
                             for a in sizes})
    open_ = _model(sizes, {a: AxisTopology(wraparound=False)
                           for a in sizes})
    w_best, w_costs = best_name(wrapped)
    o_best, o_costs = best_name(open_)
    assert w_costs["tp_row"] < w_costs["dp"], w_costs
    assert o_costs["tp_row"] > o_costs["dp"], o_costs
