"""Divisor-degree coverage: mesh factorization search + axis-threaded
rewrites (reference substitution.cc:1726-1868 per-degree instantiation and
the MachineView grid-shape enumeration, recast as: rewrites fire per mesh
axis / composite axis group, and sub-axis degrees are reached by
re-factorizing the mesh — search/mesh_search.py)."""

import sys

import pytest


def _config(mesh_axes, batch=256, argv=()):
    sys.argv = ["test"] + list(argv)
    from flexflow_tpu import FFConfig

    config = FFConfig()
    config.mesh_axis_sizes = mesh_axes
    config.batch_size = batch
    return config


def test_enumerate_factorizations():
    from flexflow_tpu.search.mesh_search import enumerate_factorizations

    shapes = enumerate_factorizations(8, ("data", "model"))
    assert len(shapes) == 4
    for s in shapes:
        assert s["data"] * s["model"] == 8
    assert {"data": 2, "model": 4} in shapes


def test_mesh_search_finds_2x4_hybrid():
    """The VERDICT acceptance case: on 8 devices, a pool-chain tower (only
    batch-partitionable — no weights, channel dim 1) plus a weight-heavy
    Linear (gradient-allreduce punishes wide DP; TP leaves the tower
    unsharded). The 2×4 hybrid must beat BOTH 8-DP and 8-TP."""
    from test_joint_search import _pcg_of

    from flexflow_tpu import FFModel
    from flexflow_tpu.search.machine_model import CHIPS
    from flexflow_tpu.search.mesh_search import search_mesh_shapes

    config = _config((8, 1, 1, 1),
                     argv=["--budget", "4", "--enable-parameter-parallel"])
    ff = FFModel(config)
    x = ff.create_tensor((256, 1, 128, 128), name="x")
    t = x
    for i in range(3):
        t = ff.pool2d(t, 2, 2, 1, 1, 0, 0, name=f"pool{i}")
    t = ff.flat(t, name="flat")
    t = ff.dense(t, 512, name="bigproj")

    g = _pcg_of(ff)
    shape, _, _, _, results = search_mesh_shapes(
        g, 8, config, chip=CHIPS["v5e"])
    costs = {(s["data"], s["model"]): c for s, c in results}
    assert shape == {"data": 2, "model": 4}, costs
    assert costs[(2, 4)] < costs[(8, 1)]
    assert costs[(2, 4)] < costs[(1, 8)]


def test_xfers_carry_axes_and_composites():
    """Every parallel-op param created by generate_all_pcg_xfers names its
    mesh axes, and composite (multi-axis) instances exist on a mesh with a
    free seq axis."""
    from flexflow_tpu.fftype import OperatorType as OT
    from flexflow_tpu.search.mesh_search import MeshSpec
    from flexflow_tpu.search.substitution import generate_all_pcg_xfers

    config = _config((2, 2, 1, 2))
    mesh = MeshSpec({"data": 2, "model": 2, "pipe": 1, "seq": 2})
    xfers = generate_all_pcg_xfers(mesh, config)
    names = {x.name for x in xfers}
    assert any("axes=dataxseq" in n for n in names), sorted(names)
    assert any("axes=modelxseq" in n for n in names), sorted(names)
    # dedup: no duplicate names
    assert len(names) == len(xfers)
    # every parallel-op params constructor in dst patterns threads axes
    # (constructors needing the match dict — e.g. the feature-dim Combine of
    # replicate_linear_combine — are covered by the e2e search tests)
    checked = 0
    for x in xfers:
        for opx in x.dst_ops:
            if opx.op_type in (OT.OP_REPARTITION, OT.OP_COMBINE,
                               OT.OP_REPLICATE, OT.OP_REDUCTION):
                try:
                    p = opx.make_params({})
                except KeyError:
                    continue
                assert p.axes, f"{x.name}: {opx.op_type} missing axes"
                checked += 1
    assert checked > 10


def test_assign_axes_uses_declared_composite():
    """A degree-4 repartition declaring axes ('data','seq') must map to
    those axes even when another mesh axis (model=4) shares the size — the
    degree→axis ambiguity the threading removes."""
    from flexflow_tpu.fftype import DataType, OperatorType as OT
    from flexflow_tpu.parallel.ops import RepartitionParams
    from flexflow_tpu.pcg.graph import Graph, OpNode
    from flexflow_tpu.search.mesh_search import MeshSpec
    from flexflow_tpu.search.substitution import (
        assign_axes_from_degrees,
        propagate_parallel_state,
    )
    from flexflow_tpu.tensor import ParallelTensor, ParallelTensorShape

    g = Graph()
    inp = OpNode(OT.OP_INPUT, None, name="x")
    inp.outputs = [ParallelTensor(
        ParallelTensorShape.from_shape((8, 16), DataType.DT_FLOAT))]
    g.add_node(inp)
    rep = OpNode(OT.OP_REPARTITION,
                 RepartitionParams(0, 4, ("data", "seq")), name="rep")
    g.add_node(rep)
    g.add_edge(inp, rep, 0, 0)
    propagate_parallel_state(g)
    mesh = MeshSpec({"data": 2, "model": 4, "seq": 2})
    assign_axes_from_degrees(g, mesh)
    assert rep.outputs[0].axis_assignment[0] == ("data", "seq")


def test_price_parallel_node_honors_declared_axes():
    """A Combine that declares its axis is priced on THAT axis — a declared
    dcn Combine prices at DCN bandwidth even though an ICI axis shares the
    degree, and vice versa (the durable fix for degree-inference)."""
    from flexflow_tpu.fftype import DataType, OperatorType as OT
    from flexflow_tpu.parallel.ops import CombineParams
    from flexflow_tpu.pcg.graph import OpNode
    from flexflow_tpu.search.cost_model import price_parallel_node
    from flexflow_tpu.search.machine_model import CHIPS, TPUMachineModel
    from flexflow_tpu.tensor import (
        ParallelDim,
        ParallelTensor,
        ParallelTensorShape,
    )

    machine = TPUMachineModel(CHIPS["v5p"], {"dcn": 2, "model": 2},
                              axis_over_dcn=frozenset({"dcn"}))

    def combine_cost(axes):
        node = OpNode(OT.OP_COMBINE, CombineParams(0, 2, axes), name="c")
        shape = ParallelTensorShape(
            (ParallelDim(1024, 2, axes=axes), ParallelDim(1024)),
            DataType.DT_FLOAT)
        node.inputs = [ParallelTensor(shape)]
        cost, comm_axes = price_parallel_node(node, machine)
        return cost, comm_axes

    dcn_cost, dcn_axes = combine_cost(("dcn",))
    ici_cost, ici_axes = combine_cost(("model",))
    assert dcn_axes == ("dcn",) and ici_axes == ("model",)
    assert dcn_cost > 5 * ici_cost


def _apply_first_match(g, xfer):
    m = next(iter(xfer.find_matches(g)))
    return xfer.apply(g, m)


def test_weight_partition_axes_ignore_batch_dim():
    """Nested dp×tp rewrites on a mesh where data and model share a size:
    the column-TP kernel must shard over the REPLICA dim's axes ('model'),
    never the batch dim's ('data') even though both carry the same
    degree."""
    from test_joint_search import _pcg_of

    from flexflow_tpu import ActiMode, FFModel
    from flexflow_tpu.fftype import ActiMode as AM
    from flexflow_tpu.search.mesh_search import MeshSpec
    from flexflow_tpu.search.substitution import (
        assign_axes_from_degrees,
        create_partition_linear_combine,
        create_replicate_linear_combine,
    )

    config = _config((2, 2, 1, 1), batch=8)
    ff = FFModel(config)
    x = ff.create_tensor((8, 16), name="x")
    ff.dense(x, 16, ActiMode.AC_MODE_NONE, name="fc")
    g = _pcg_of(ff)
    g = _apply_first_match(
        g, create_partition_linear_combine(2, AM.AC_MODE_NONE, ("data",)))
    g = _apply_first_match(
        g, create_replicate_linear_combine(2, AM.AC_MODE_NONE, ("model",)))
    assign_axes_from_degrees(g, MeshSpec({"data": 2, "model": 2}))
    lin = next(n for n in g.topo_order()
               if n.op_type.name == "OP_LINEAR")
    spec = lin.weight_axes["kernel"]
    assert "model" in str(spec) and "data" not in str(spec), spec


def test_nested_same_axis_partition_rejected():
    """Applying the same axis-bound partition twice must be rejected at
    costing (a mesh axis cannot shard one tensor twice) instead of
    reaching the executor as PartitionSpec(('data','data'))."""
    from test_joint_search import _pcg_of

    from flexflow_tpu import ActiMode, FFModel
    from flexflow_tpu.fftype import ActiMode as AM
    from flexflow_tpu.search.mesh_search import MeshSpec
    from flexflow_tpu.search.substitution import (
        assign_axes_from_degrees,
        create_partition_linear_combine,
    )

    config = _config((2, 1, 1, 1), batch=8)
    ff = FFModel(config)
    x = ff.create_tensor((8, 16), name="x")
    ff.dense(x, 16, ActiMode.AC_MODE_NONE, name="fc")
    g = _pcg_of(ff)
    xfer = create_partition_linear_combine(2, AM.AC_MODE_NONE, ("data",))
    g = _apply_first_match(g, xfer)
    g2 = _apply_first_match(g, xfer)
    with pytest.raises(ValueError, match="used twice|already sharding"):
        assign_axes_from_degrees(g2, MeshSpec({"data": 2}))


def test_compile_with_mesh_shape_search_trains():
    """--search-mesh-shapes end to end: compile re-factorizes the mesh and
    the chosen plan trains."""
    import numpy as np

    from flexflow_tpu import (
        ActiMode,
        FFModel,
        LossType,
        MetricsType,
        SGDOptimizer,
    )

    config = _config((8, 1, 1, 1), batch=64,
                     argv=["--budget", "2", "--search-mesh-shapes",
                           "--enable-parameter-parallel"])
    ff = FFModel(config)
    x = ff.create_tensor((64, 32))
    t = ff.dense(x, 64, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 10, name="head")
    t = ff.softmax(t, name="sm")
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])
    sizes = dict(ff.mesh.shape)
    n = 1
    for v in sizes.values():
        n *= v
    assert n == 8, sizes
    rs = np.random.RandomState(0)
    xs = rs.randn(128, 32).astype(np.float32)
    ys = rs.randint(0, 10, (128, 1)).astype(np.int32)
    ff.fit(xs, ys, epochs=1)
