"""ffpulse metrics-plane tests (telemetry/metrics.py, telemetry/export.py,
serving instrumentation, docs/observability.md "metrics plane").

The acceptance surface of the mergeable metrics plane:

  - bucket-estimated percentiles land within ONE bucket width of the
    exact sample percentile (the log4 table's 10^0.25 ratio);
  - merge_snapshots is associative and order-independent (the property
    that makes coordinator-side cross-host merge well-defined);
  - the Prometheus text exposition round-trips counters, gauges, and
    histogram counts/sum/count through parse_prometheus;
  - with telemetry OFF, a serving step allocates NO metric objects —
    every series the hot path touches is pre-created at engine build
    (the zero-cost-off overhead guard), and the module-level
    inc/observe/set_gauge dispatchers are no-ops without a session;
  - engine.metrics_summary() is callable MID-RUN, and at drain its
    payload matches the serve.summary event bit for bit;
  - `no_token_requests` pins the drain-accounting gap: requests that
    have not produced a first token are counted there and excluded
    from the TTFT histogram's denominator by design;
  - the fflint `raw_timer_in_hot_path` rule catches a bare timer pair
    in a step/decode/prefill function, stays quiet for gated reads,
    pragma'd lines, non-hot-path functions, and telemetry/ files.
"""

import sys

import numpy as np
import pytest


def _lm_config():
    from flexflow_tpu.models import TransformerLMConfig

    return TransformerLMConfig(
        vocab_size=64, hidden_size=32, num_heads=4, num_layers=2,
        sequence_length=32, attention_impl="xla")


def _build_lm(batch=1, argv=()):
    sys.argv = ["test"] + list(argv)
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import build_transformer_lm

    cfg = FFConfig()
    if cfg.mesh_axis_sizes is None:
        cfg.mesh_axis_sizes = (1, 1, 1, 1)
    cfg.batch_size = batch
    ff = FFModel(cfg)
    build_transformer_lm(ff, _lm_config(), batch_size=batch)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff


PROMPTS = [[3, 7, 11, 2, 5], [5, 2], [1, 9, 30, 30, 12, 4, 8], [60, 1, 2]]


# ---------------------------------------------------------- pure registry


def test_percentile_within_one_bucket_width():
    """Bucket-estimated p50/p95/p99 over a lognormal sample sit within
    one log4 bucket (ratio 10^0.25) of the exact sample percentile."""
    from flexflow_tpu.telemetry.metrics import (
        MetricsRegistry, percentile_from_hist,
    )

    rs = np.random.RandomState(11)
    samples = rs.lognormal(mean=-4.0, sigma=1.0, size=2000)
    reg = MetricsRegistry()
    h = reg.histogram("lat_s")
    for v in samples:
        h.observe(float(v))
    hd = reg.snapshot()["histograms"]["lat_s"]
    width = 10.0 ** 0.25  # one log4 bucket
    for q in (50.0, 95.0, 99.0):
        exact = float(np.percentile(samples, q))
        est = percentile_from_hist(hd, q)
        assert exact / width <= est <= exact * width, (
            f"p{q}: estimate {est} more than one bucket from {exact}")


def test_merge_associative_and_order_independent():
    """merge over N simulated hosts gives one answer no matter the
    grouping or order — counters/counts/sums add, min/max extremize."""
    from flexflow_tpu.telemetry.metrics import (
        MetricsRegistry, merge_snapshots,
    )

    rs = np.random.RandomState(3)
    snaps = []
    for host in range(3):
        reg = MetricsRegistry()
        c = reg.counter("train_tokens_total")
        h = reg.histogram("train_step_time_s")
        g = reg.gauge("slots_active", host=str(host))
        for v in rs.lognormal(-2.0, 1.0, size=50 * (host + 1)):
            h.observe(float(v))
            c.inc(8.0)
        g.set(float(host + 1))
        snaps.append(reg.snapshot())

    a = merge_snapshots(snaps)
    b = merge_snapshots([merge_snapshots(snaps[:2]), snaps[2]])
    c = merge_snapshots([snaps[2], snaps[0], snaps[1]])
    assert a == b == c
    hist = a["histograms"]["train_step_time_s"]
    assert hist["count"] == 50 + 100 + 150
    assert sum(hist["counts"]) == hist["count"]
    assert a["counters"]["train_tokens_total"] == 8.0 * 300
    # per-host labeled gauges survive as distinct series
    assert a["gauges"]['slots_active{host="2"}'] == 3.0


def test_prometheus_round_trip():
    """to_prometheus -> parse_prometheus preserves counters, gauges, and
    histogram counts/sum/count (min/max are not part of the exposition
    format and are dropped by design)."""
    from flexflow_tpu.telemetry.metrics import (
        MetricsRegistry, parse_prometheus, to_prometheus,
    )

    reg = MetricsRegistry()
    reg.counter("serve_tokens_out_total").inc(41.0)
    reg.gauge("serve_slots_active", host="0").set(3.0)
    h = reg.histogram("serve_ttft_s")
    for v in (0.01, 0.02, 0.5, 1.7):
        h.observe(v)
    snap = reg.snapshot()
    back = parse_prometheus(to_prometheus(snap))
    assert back["counters"] == snap["counters"]
    assert back["gauges"] == snap["gauges"]
    want = snap["histograms"]["serve_ttft_s"]
    got = back["histograms"]["serve_ttft_s"]
    assert got["counts"] == want["counts"]
    assert got["count"] == want["count"]
    assert got["sum"] == pytest.approx(want["sum"])


# ------------------------------------------------------- overhead guard


def test_telemetry_off_step_allocates_no_metric_objects():
    """With no telemetry session, draining a full trace creates ZERO new
    series on the engine registry — every series the hot path touches is
    pre-created at engine build — and the module-level dispatchers are
    one-global-read no-ops."""
    from flexflow_tpu import telemetry

    assert telemetry._active is None
    # module dispatchers: no session -> no-op, no error, no allocation
    telemetry.inc("never_created_total")
    telemetry.observe("never_created_s", 0.5)
    telemetry.set_gauge("never_created", 1.0)

    ff = _build_lm()
    eng = ff.serve(slots=2, max_new_tokens=4, prefill_chunk=4)
    n0 = len(eng.metrics)
    for p in PROMPTS:
        eng.submit(p)
    while not eng.scheduler.drained:
        eng.step()
    assert len(eng.metrics) == n0, (
        "serving steps allocated metric objects — the overhead guard "
        "requires every hot-path series pre-created in __init__")
    # the pre-created plane actually recorded the run
    snap = eng.metrics.snapshot()
    assert snap["histograms"]["serve_ttft_s"]["count"] == len(PROMPTS)
    assert snap["counters"]["serve_tokens_generated_total"] == (
        4.0 * len(PROMPTS))


# ------------------------------------------------- summary + accounting


def test_midrun_summary_matches_drain_summary(tmp_path):
    """metrics_summary() works mid-run (old drain-only keys preserved),
    and at drain the serve.summary event carries exactly the summary a
    caller reads off the engine afterwards."""
    ff = _build_lm()
    ff.enable_telemetry(str(tmp_path / "tel"))
    eng = ff.serve(slots=2, max_new_tokens=4, prefill_chunk=4)
    for p in PROMPTS:
        eng.submit(p)
    for _ in range(3):
        eng.step()
    mid = eng.metrics_summary()  # mid-run: must not throw, old keys live
    for key in ("requests_completed", "kv_layout", "no_token_requests"):
        assert key in mid
    assert mid["requests_completed"] <= len(PROMPTS)

    import time

    t0 = time.perf_counter()
    while not eng.scheduler.drained:
        eng.step()
    eng.note_drain(time.perf_counter() - t0)
    final = eng.metrics_summary()
    eng.telemetry.close()

    from flexflow_tpu.telemetry import read_jsonl

    recs = read_jsonl(str(tmp_path / "tel" / "metrics.jsonl"))
    summaries = [r for r in recs if r["kind"] == "serve.summary"]
    assert summaries
    event = summaries[-1]
    for key, want in final.items():
        assert key in event, f"serve.summary missing {key!r}"
        if isinstance(want, float):
            assert event[key] == pytest.approx(want), key
        else:
            assert event[key] == want, key
    # drained snapshot landed with the self-consistency the doctor checks
    drained = [r for r in recs if r.get("kind") == "metrics_snapshot"
               and r.get("drained")]
    assert drained
    hists = drained[-1]["metrics"]["histograms"]
    assert hists["serve_ttft_s"]["count"] == len(PROMPTS)
    for h in hists.values():
        assert sum(h["counts"]) == h["count"]


def test_no_token_requests_excluded_from_ttft():
    """Satellite pin: requests that have not yet produced a first token
    are counted in stats()['no_token_requests'] and are NOT in the TTFT
    histogram's denominator — submitted-but-unstepped requests show up
    there, and after the drain the key returns to zero with TTFT count
    equal to completed requests."""
    ff = _build_lm()
    eng = ff.serve(slots=2, max_new_tokens=4, prefill_chunk=4)
    for p in PROMPTS:
        eng.submit(p)
    st = eng.stats()
    assert st["no_token_requests"] == len(PROMPTS)
    assert eng.metrics.snapshot()["histograms"]["serve_ttft_s"]["count"] == 0
    while not eng.scheduler.drained:
        eng.step()
    st = eng.stats()
    assert st["no_token_requests"] == 0
    assert (eng.metrics.snapshot()["histograms"]["serve_ttft_s"]["count"]
            == st["requests_completed"] == len(PROMPTS))


# ----------------------------------------------------------- fflint rule


_HOT = """
import time

def decode_step(batch):
    t0 = time.perf_counter()
    out = run(batch)
    dt = time.perf_counter() - t0
    return out, dt
"""

_GATED = """
import time

def decode_step(batch, tel):
    if tel is not None:
        t0 = time.perf_counter()
    out = run(batch)
    if tel is not None:
        dt = time.perf_counter() - t0
    return out
"""

_PRAGMA = """
import time

def decode_step(batch):
    t0 = time.perf_counter()
    out = run(batch)
    dt = time.perf_counter() - t0  # fflint: ok raw_timer_in_hot_path
    return out, dt
"""

_COLD = """
import time

def load_checkpoint(path):
    t0 = time.perf_counter()
    data = read(path)
    return data, time.perf_counter() - t0
"""


def test_lint_raw_timer_in_hot_path_matrix():
    from flexflow_tpu.analysis.lint import lint_source

    def hits(src, path="flexflow_tpu/serving/engine.py"):
        return [f for f in lint_source(src, path)
                if f.code == "raw_timer_in_hot_path"]

    found = hits(_HOT)
    assert len(found) == 1
    assert found[0].severity == "warning"
    assert "decode_step" in found[0].message
    # gated reads are the sanctioned idiom; pragma suppresses; a lone
    # read is not a pair; cold-path names and telemetry/ files are out
    assert hits(_GATED) == []
    assert hits(_PRAGMA) == []
    assert hits(_COLD) == []
    assert hits(_HOT, path="flexflow_tpu/telemetry/session.py") == []
