"""Mixed-precision policy tests.

The reference's `allow_tensor_op_math_conversion` flag flips cublas into
tensor-op math (model.cc:3676); the TPU recast is bf16 MXU input casting
(ops/base.py matmul_cast) plus a full bf16-activation policy with fp32
master weights (config.computation_dtype, executor._cast_compute).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flexflow_tpu import (
    ActiMode,
    FFConfig,
    FFModel,
    LossType,
    MetricsType,
    SGDOptimizer,
)
from flexflow_tpu.fftype import DataType


def _blob_data(rs, n=512, dim=16, classes=8):
    c = rs.randn(classes, dim) * 3
    y = rs.randint(0, classes, n)
    x = (c[y] + rs.randn(n, dim)).astype(np.float32)
    return x, y.reshape(-1, 1).astype(np.int32)


def _mlp(config):
    ff = FFModel(config)
    x = ff.create_tensor((config.batch_size, 16), name="input_0")
    t = ff.dense(x, 32, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.softmax(ff.dense(t, 8, name="fc2"), name="sm")
    ff.compile(
        optimizer=SGDOptimizer(lr=0.1),
        loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        metrics=[MetricsType.METRICS_ACCURACY],
    )
    return ff


def test_bf16_policy_trains_with_fp32_master_weights(rng):
    config = FFConfig()
    config.batch_size = 64
    config.epochs = 3
    config.computation_dtype = DataType.DT_BFLOAT16
    ff = _mlp(config)
    x, y = _blob_data(rng)
    ff.fit(x, y)
    # master weights stay fp32 even though compute ran in bf16
    for node_params in ff._params.values():
        for arr in node_params.values():
            assert arr.dtype == jnp.float32
    assert ff.get_perf_metrics().get_accuracy() > 0.9


def test_bf16_policy_matches_fp32_loss_coarsely(rng):
    """The bf16 step must track the fp32 step (policy keeps loss/stats fp32,
    so first-step losses agree to bf16 resolution)."""
    losses = {}
    for cd in (None, DataType.DT_BFLOAT16):
        config = FFConfig()
        config.batch_size = 64
        config.computation_dtype = cd
        ff = _mlp(config)
        x, y = _blob_data(np.random.RandomState(0))
        ff.start_batch(x[:64], y[:64])
        losses[cd] = float(ff.backward())
    assert abs(losses[None] - losses[DataType.DT_BFLOAT16]) < 0.05


def test_tensor_op_math_casts_matmul_inputs():
    """force_tensor_op_math exercises the MXU-input-cast path on CPU: fp32
    activations, bf16 matmul inputs, fp32 accumulation."""
    config = FFConfig()
    config.batch_size = 8
    config.force_tensor_op_math = True
    ff = _mlp(config)
    x = np.random.RandomState(1).randn(8, 16).astype(np.float32)
    logits, _ = ff.executor.build_forward()(
        ff._params, ff._state, {"input_0": x}, False
    )
    assert logits.dtype == jnp.float32
    # value must differ from pure-fp32 math by a bf16-rounding-sized amount
    config2 = FFConfig()
    config2.batch_size = 8
    ff2 = _mlp(config2)
    for name, p in ff._params.items():
        for k, v in p.items():
            ff2._params[name][k] = v
    logits2, _ = ff2.executor.build_forward()(
        ff2._params, ff2._state, {"input_0": x}, False
    )
    diff = float(jnp.max(jnp.abs(logits - logits2)))
    # lower bound proves the cast actually happened; upper bound proves the
    # math is still the same up to bf16 rounding
    assert 0.0 < diff < 0.05


def test_bf16_state_dtype_stable_across_steps(rng):
    """Running stats stay fp32 across steps so the jitted signature is
    stable (no silent recompiles)."""
    config = FFConfig()
    config.batch_size = 8
    config.computation_dtype = DataType.DT_BFLOAT16
    ff = FFModel(config)
    x = ff.create_tensor((8, 3, 8, 8))
    t = ff.conv2d(x, 4, 3, 3, 1, 1, 1, 1)
    t = ff.batch_norm(t)
    t = ff.flat(t)
    t = ff.softmax(ff.dense(t, 4))
    ff.compile(
        optimizer=SGDOptimizer(lr=0.01),
        loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
    )
    xs = rng.randn(16, 3, 8, 8).astype(np.float32)
    ys = rng.randint(0, 4, (16, 1)).astype(np.int32)
    ff.fit(xs, ys, epochs=2)  # 2 batches/epoch → signature must be stable
    for node_state in ff._state.values():
        for arr in node_state.values():
            assert arr.dtype == jnp.float32


def test_dtype_cli_flag():
    import sys

    old = sys.argv
    try:
        sys.argv = ["t", "--dtype", "bf16"]
        config = FFConfig()
        assert config.computation_dtype == DataType.DT_BFLOAT16
        sys.argv = ["t", "--dtype", "fp32"]
        config = FFConfig()
        assert config.computation_dtype is None
    finally:
        sys.argv = old
