"""Model zoo tests: every reference example family builds, shape-infers, and
(for the light ones) trains a step on the virtual mesh (SURVEY §2.6)."""

import sys

import numpy as np
import pytest


def _ff(mesh=(1, 1, 1, 1), batch=8):
    sys.argv = ["test", "-b", str(batch)]
    from flexflow_tpu import FFConfig, FFModel

    config = FFConfig()
    config.mesh_axis_sizes = mesh
    config.batch_size = batch
    return FFModel(config)


def test_transformer_reference_builds():
    from flexflow_tpu.models import TransformerConfig, build_transformer

    ff = _ff(batch=4)
    c = TransformerConfig(hidden_size=64, num_heads=4, num_layers=2,
                          sequence_length=16)
    inp, out = build_transformer(ff, c, batch_size=4)
    assert out.dims == (4, 16, 1)
    assert len(ff.layers) == 2 * 3 + 1


def test_transformer_trains():
    from flexflow_tpu import LossType, SGDOptimizer
    from flexflow_tpu.models import TransformerConfig, build_transformer

    ff = _ff(batch=4)
    c = TransformerConfig(hidden_size=32, num_heads=2, num_layers=1,
                          sequence_length=8)
    inp, out = build_transformer(ff, c, batch_size=4)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE)
    rs = np.random.RandomState(0)
    x = rs.randn(8, 8, 32).astype(np.float32)
    y = rs.randn(8, 8, 1).astype(np.float32)
    ff.fit(x, y, epochs=1, batch_size=4)


def test_transformer_lm_trains():
    from flexflow_tpu import LossType, SGDOptimizer
    from flexflow_tpu.models import TransformerLMConfig, build_transformer_lm

    ff = _ff(batch=2)
    c = TransformerLMConfig(vocab_size=64, hidden_size=32, num_heads=2,
                            num_layers=1, sequence_length=16,
                            attention_impl="xla")
    tokens, logits = build_transformer_lm(ff, c, batch_size=2)
    assert logits.dims == (2, 16, 64)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    rs = np.random.RandomState(0)
    toks = rs.randint(0, 64, (4, 16)).astype(np.int32)
    pos = np.tile(np.arange(16, dtype=np.int32), (4, 1))
    labels = rs.randint(0, 64, (4, 16, 1)).astype(np.int32)
    ff.fit({"tokens": toks, "positions": pos}, labels, epochs=1, batch_size=2)


def test_mnist_mlp_builds():
    from flexflow_tpu.models import build_mnist_mlp

    ff = _ff(batch=8)
    inp, out = build_mnist_mlp(ff)
    assert out.dims == (8, 10)


def test_mlp_unify_trains():
    from flexflow_tpu import LossType, SGDOptimizer
    from flexflow_tpu.models import build_mlp_unify

    ff = _ff(batch=4)
    inputs, out = build_mlp_unify(ff, batch_size=4, in_dim=16,
                                  hidden_dims=(32, 10))
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    rs = np.random.RandomState(0)
    x = {"input1": rs.randn(8, 16).astype(np.float32),
         "input2": rs.randn(8, 16).astype(np.float32)}
    y = rs.randint(0, 10, (8, 1)).astype(np.int32)
    ff.fit(x, y, epochs=1, batch_size=4)


def test_alexnet_builds():
    from flexflow_tpu.models import build_alexnet

    ff = _ff(batch=2)
    inp, out = build_alexnet(ff, batch_size=2)
    assert out.dims == (2, 10)


def test_resnet50_builds():
    from flexflow_tpu.models import build_resnet50

    ff = _ff(batch=2)
    inp, out = build_resnet50(ff, batch_size=2)
    assert out.dims == (2, 10)
    # 50 convolutional layers + fc (projections excluded): count conv ops
    from flexflow_tpu.fftype import OperatorType as OT

    convs = [l for l in ff.layers if l.op_type == OT.OP_CONV2D]
    assert len(convs) == 1 + 16 * 3 + 4  # stem + 16 bottlenecks + 4 proj


def test_resnext50_builds():
    from flexflow_tpu.models import build_resnext50

    ff = _ff(batch=2)
    inp, out = build_resnext50(ff, batch_size=2)
    assert out.dims == (2, 10)


def test_inception_builds():
    from flexflow_tpu.models import build_inception_v3

    ff = _ff(batch=2)
    inp, out = build_inception_v3(ff, batch_size=2)
    assert out.dims == (2, 10)


def test_dlrm_trains():
    from flexflow_tpu import LossType, SGDOptimizer
    from flexflow_tpu.models import DLRMConfig, build_dlrm

    ff = _ff(batch=4)
    c = DLRMConfig(sparse_feature_size=8, embedding_size=(50, 60),
                   mlp_bot=(4, 8, 8), mlp_top=(24, 16, 2))
    inputs, out = build_dlrm(ff, c, batch_size=4)
    assert out.dims == (4, 2)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE)
    rs = np.random.RandomState(0)
    x = {
        "sparse0": rs.randint(0, 50, (8, 1)).astype(np.int64),
        "sparse1": rs.randint(0, 60, (8, 1)).astype(np.int64),
        "dense_input": rs.randn(8, 4).astype(np.float32),
    }
    y = rs.randn(8, 2).astype(np.float32)
    ff.fit(x, y, epochs=1, batch_size=4)


def test_xdl_builds():
    from flexflow_tpu.models import build_xdl
    from flexflow_tpu.models.xdl import XDLConfig

    ff = _ff(batch=4)
    c = XDLConfig(sparse_feature_size=8, embedding_size=(50, 60),
                  mlp_top=(16, 2))
    inputs, out = build_xdl(ff, c, batch_size=4)
    assert out.dims == (4, 2)


def test_candle_uno_trains():
    from flexflow_tpu import LossType, SGDOptimizer
    from flexflow_tpu.models import build_candle_uno
    from flexflow_tpu.models.candle_uno import CandleUnoConfig

    ff = _ff(batch=4)
    c = CandleUnoConfig(
        dense_layers=(16, 16), dense_feature_layers=(16, 16),
        feature_shapes={"dose": 1, "cell.rnaseq": 30,
                        "drug.descriptors": 40, "drug.fingerprints": 20},
    )
    inputs, out = build_candle_uno(ff, c, batch_size=4)
    assert out.dims == (4, 1)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE)
    rs = np.random.RandomState(0)
    x = {t.name: rs.randn(8, t.dims[1]).astype(np.float32)
         for t in inputs}
    y = rs.randn(8, 1).astype(np.float32)
    ff.fit(x, y, epochs=1, batch_size=4)


@pytest.mark.parametrize("fused", [False, True])
def test_moe_trains(fused):
    from flexflow_tpu import LossType, SGDOptimizer
    from flexflow_tpu.models import MoeConfig, build_moe

    ff = _ff(batch=8)
    c = MoeConfig(num_exp=4, num_select=2, in_dim=16, num_classes=10)
    inp, out = build_moe(ff, c, batch_size=8, fused=fused)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    rs = np.random.RandomState(0)
    x = rs.randn(16, 16).astype(np.float32)
    y = rs.randint(0, 10, (16, 1)).astype(np.int32)
    ff.fit(x, y, epochs=1, batch_size=8)


def test_moe_encoder_builds():
    from flexflow_tpu.models import MoeConfig
    from flexflow_tpu.models.moe import build_moe_encoder

    ff = _ff(batch=2)
    c = MoeConfig(num_exp=4, num_select=2, hidden_size=16,
                  num_attention_heads=2, num_encoder_layers=1)
    inp, out = build_moe_encoder(ff, c, batch_size=2, seq_length=8)
    assert out.dims == (2, 10)


def test_lm_metrics_sane():
    """Accuracy/sparse-CCE must count every token position for LM-shaped
    logits (b, s, vocab)."""
    import jax.numpy as jnp
    from flexflow_tpu.fftype import LossType, MetricsType
    from flexflow_tpu.metrics import Metrics, PerfMetrics

    m = Metrics.from_list(
        LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
        [MetricsType.METRICS_ACCURACY],
    )
    b, s, v = 2, 4, 8
    labels = np.arange(b * s).reshape(b, s, 1) % v
    logits = np.full((b, s, v), 0.01, np.float32)
    for i in range(b):
        for j in range(s):
            logits[i, j, labels[i, j, 0]] = 1.0  # all predictions correct
    c = m.compute(m.zero_counters(), jnp.asarray(logits), jnp.asarray(labels))
    pm = PerfMetrics({k: np.asarray(val) for k, val in c.items()}, m)
    assert pm.train_all == b * s
    assert pm.get_accuracy() == 1.0
