"""MoE parity tests: expert parallelism for the unfused reference trio via
the fuse_moe_trio rewrite (examples/cpp/mixture_of_experts attribute-parallel
views recast), AggregateSpec label replication (model.cc:2875) trained e2e,
and Cache staleness scoring (cache.h:14-65)."""

import sys

import numpy as np
import pytest


def _config(mesh_axes, batch=16, argv=()):
    sys.argv = ["test"] + list(argv)
    from flexflow_tpu import FFConfig

    config = FFConfig()
    config.mesh_axis_sizes = mesh_axes
    config.batch_size = batch
    return config


def test_fuse_moe_trio_rewrite():
    """The rewrite matches the unfused group_by → dense×n → aggregate trio
    and produces a stacked Experts node with the right params."""
    from flexflow_tpu import FFModel
    from flexflow_tpu.fftype import OperatorType as OT
    from flexflow_tpu.models import MoeConfig, build_moe
    from flexflow_tpu.search.substitution import create_fuse_moe_trio
    from tests.test_joint_search import _pcg_of

    config = _config((2, 4, 1, 1), batch=16)
    ff = FFModel(config)
    mc = MoeConfig(num_exp=4, num_select=2, in_dim=32, num_classes=8)
    build_moe(ff, mc, batch_size=16, fused=False)
    g = _pcg_of(ff)
    assert any(n.op_type == OT.OP_GROUP_BY for n in g.topo_order())

    xfer = create_fuse_moe_trio(4)
    matches = xfer.find_matches(g)
    assert matches, "fuse_moe_trio found no match on the unfused MoE"
    ng = xfer.apply(g, matches[0])
    types = [n.op_type for n in ng.topo_order()]
    assert OT.OP_EXPERTS in types
    assert OT.OP_GROUP_BY not in types and OT.OP_AGGREGATE not in types
    exp = next(n for n in ng.topo_order() if n.op_type == OT.OP_EXPERTS)
    assert exp.params.n == 4
    assert exp.params.hidden_size == 8  # expert dense out = num_classes
    assert exp.params.alpha == mc.alpha
    assert exp.params.lambda_bal == mc.lambda_bal
    # the fresh Experts node declares its stacked weights
    names = {ws.name for ws in exp.weight_specs}
    assert "kernel" in names


def test_unfused_moe_search_reaches_expert_parallel():
    """Joint search on the UNFUSED MoE: the fuse rewrite fires and the
    stacked kernel can shard over the model axis — EP for the
    reference-parity path."""
    from flexflow_tpu import FFModel
    from flexflow_tpu.fftype import OperatorType as OT
    from flexflow_tpu.models import MoeConfig, build_moe
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.joint import joint_graph_optimize
    from flexflow_tpu.search.machine_model import machine_model_for_mesh
    from flexflow_tpu.machine import build_mesh
    from tests.test_joint_search import _pcg_of

    config = _config((2, 4, 1, 1), batch=64,
                     argv=["--budget", "8", "--enable-attribute-parallel",
                           "--search-overlap-backward-update"])
    ff = FFModel(config)
    # large experts so the fused+sharded plan wins on cost
    mc = MoeConfig(num_exp=4, num_select=2, in_dim=512, num_classes=512,
                   alpha=2.0)
    build_moe(ff, mc, batch_size=64, fused=False)
    g = _pcg_of(ff)
    mesh = build_mesh(config.mesh_shape())
    cm = CostModel(machine_model_for_mesh(mesh))
    best_g, choice, us = joint_graph_optimize(g, mesh, config, cm)
    experts = [n for n in best_g.topo_order() if n.op_type == OT.OP_EXPERTS]
    assert experts, "search did not fuse the MoE trio"
    cfg = choice.get(experts[0].guid)
    assert cfg is not None and cfg.name == "ep", (
        f"expected ep sharding on the fused Experts, got "
        f"{cfg.name if cfg else None}")


def test_unfused_moe_trains_through_search():
    """8-device dryrun: unfused MoE compiled through the joint search (fuse
    rewrite live) executes a training epoch and learns."""
    from flexflow_tpu import FFModel, LossType, MetricsType, SGDOptimizer
    from flexflow_tpu.models import MoeConfig, build_moe

    config = _config((2, 4, 1, 1), batch=32,
                     argv=["--budget", "6", "--enable-attribute-parallel"])
    ff = FFModel(config)
    mc = MoeConfig(num_exp=4, num_select=2, in_dim=32, num_classes=10,
                   alpha=2.0)
    build_moe(ff, mc, batch_size=32, fused=False)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])
    rs = np.random.RandomState(0)
    c = rs.randn(10, 32) * 3
    y = rs.randint(0, 10, 512)
    xs = (c[y] + rs.randn(512, 32)).astype(np.float32)
    ff.fit(xs, y.reshape(-1, 1).astype(np.int32), epochs=3)
    assert ff.get_perf_metrics().get_accuracy() >= 0.6


def _build_agg_spec_model(ff, n=4, k=2, in_dim=32, classes=8, batch=16):
    from flexflow_tpu import ActiMode

    x = ff.create_tensor((batch, in_dim), name="input")
    gate = ff.dense(x, n, ActiMode.AC_MODE_RELU, name="gate")
    probs = ff.softmax(gate, name="gate_sm")
    vals, assign = ff.top_k(probs, k)
    experts_in = ff.group_by(x, assign, n, 2.0, name="gb")
    outs = [ff.dense(ei, classes, ActiMode.AC_MODE_RELU, name=f"exp{i}")
            for i, ei in enumerate(experts_in)]
    t = ff.aggregate_spec([vals, assign, assign, probs] + outs, n,
                          name="agg_spec")
    return ff.softmax(t, name="sm")


def test_aggregate_spec_trains_with_replicated_labels():
    """AggregateSpec as the output head: logits are (k*b, classes) and the
    executor replicates labels k× (model.cc:2875) so the SCCE loss and
    metrics line up; training runs and improves."""
    from flexflow_tpu import FFModel, LossType, MetricsType, SGDOptimizer

    k, batch = 2, 16
    config = _config((2, 1, 1, 1), batch=batch)
    ff = FFModel(config)
    _build_agg_spec_model(ff, n=4, k=k, batch=batch)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])
    assert ff.executor.label_replication == k

    rs = np.random.RandomState(0)
    c = rs.randn(8, 32) * 3
    y = rs.randint(0, 8, 256)
    xs = (c[y] + rs.randn(256, 32)).astype(np.float32)
    ff.fit(xs, y.reshape(-1, 1).astype(np.int32), epochs=3)
    m = ff.get_perf_metrics()
    # every batch contributes k*b samples
    assert m.train_all == 3 * 256 * k
    assert m.get_accuracy() >= 0.5


def test_cache_staleness_score():
    """Cache scores its cached activation against the live batch: fully
    stale (1.0) on the first step, fresh (0.0) when the same batch repeats
    (cache.h:14-65 score semantics)."""
    from flexflow_tpu import FFModel, LossType, SGDOptimizer

    config = _config((1, 1, 1, 1), batch=8)
    ff = FFModel(config)
    x = ff.create_tensor((8, 16), name="input")
    t = ff.cache(x, num_batches=4, name="cache0")
    t = ff.dense(t, 4, name="head")
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE)

    rs = np.random.RandomState(0)
    xs = rs.randn(8, 16).astype(np.float32)
    ys = rs.randn(8, 4).astype(np.float32)
    step = ff.executor.build_train_step()
    import jax

    state = (ff._params, ff._state, ff._opt_slots, ff._step, ff._counters)
    batch = ff._make_batch({"input": xs}, ys)
    out = step(*state, jax.random.key(0), batch)
    s1 = float(out[1]["cache0"]["score"])
    assert s1 == pytest.approx(1.0), "empty cache must score fully stale"
    out2 = step(out[0], out[1], out[2], out[3], out[4],
                jax.random.key(1), batch)
    s2 = float(out2[1]["cache0"]["score"])
    assert s2 == pytest.approx(0.0, abs=1e-5), (
        "repeating the same batch must score fresh")
