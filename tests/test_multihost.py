"""Multi-host/DCN tests on the virtual 8-device CPU mesh: (dcn, data, model)
mesh construction, DCN-priced collectives in the machine model, the
search-on-host-0 plan broadcast helpers, and end-to-end training on a
multi-host-shaped mesh (reference: mapper.cc:291-306, MULTI-NODE.md; recipe
in MULTIHOST.md)."""

import sys

import numpy as np
import pytest


def test_mesh_shape_with_nodes_flag():
    sys.argv = ["t", "--nodes", "2", "--mesh", "2,2,1,1"]
    from flexflow_tpu import FFConfig
    from flexflow_tpu.machine import MULTIHOST_AXES

    c = FFConfig()
    ms = c.mesh_shape()
    assert ms.axis_names == MULTIHOST_AXES
    assert ms.axis_sizes == (2, 2, 2, 1, 1)


def test_mesh_shape_explicit_five_axes():
    sys.argv = ["t", "--mesh", "2,2,2,1,1"]
    from flexflow_tpu import FFConfig
    from flexflow_tpu.machine import MULTIHOST_AXES

    c = FFConfig()
    ms = c.mesh_shape()
    assert ms.axis_names == MULTIHOST_AXES
    assert ms.axis_sizes == (2, 2, 2, 1, 1)


def test_machine_model_prices_dcn_axis():
    from flexflow_tpu.search.machine_model import CHIPS, machine_model_for_mesh

    m = machine_model_for_mesh({"dcn": 2, "data": 2, "model": 2},
                               chip=CHIPS["v5p"])
    assert "dcn" in m.axis_over_dcn
    # same payload, same axis size: DCN must be far slower than ICI
    assert m.all_reduce(1e8, "dcn") > 5 * m.all_reduce(1e8, "data")
    # the torus-fold heuristic must not give the DCN axis extra ICI links
    assert m.axis_links["dcn"] == 1


def test_broadcast_json_single_process_passthrough():
    from flexflow_tpu.distributed import broadcast_json, run_search_on_host0
    from flexflow_tpu.parallel.strategies import Strategy

    payload = {"version": 1, "nodes": {"fc1": {
        "outputs": {"0": [["dcn", "data"], []]}, "weights": {}}}}
    assert broadcast_json(payload) == payload

    s = Strategy()
    s.set_output("fc1", 0, (("dcn", "data"), ()))
    got = run_search_on_host0(lambda: s)
    assert got["fc1"]["outputs"][0] == (("dcn", "data"), ())


def test_train_on_dcn_mesh():
    """End-to-end: (dcn=2, data=2, model=2) mesh, batch sharded over
    (dcn, data), searched TP over `model`, converges."""
    sys.argv = ["t", "--mesh", "2,2,2,1,1", "--budget", "4",
                "--enable-parameter-parallel"]
    from flexflow_tpu import (
        ActiMode, FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
    )

    config = FFConfig()
    config.batch_size = 32
    ff = FFModel(config)
    x = ff.create_tensor((32, 32))
    t = ff.dense(x, 64, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 64, ActiMode.AC_MODE_RELU, name="fc2")
    t = ff.softmax(ff.dense(t, 10, name="out"))
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])
    assert dict(ff.mesh.shape)["dcn"] == 2

    # data-parallel default composes (dcn, data) on the batch dim
    from flexflow_tpu.fftype import OperatorType as OT

    input_node = next(n for n in ff.graph.topo_order()
                      if n.op_type == OT.OP_INPUT)
    assert input_node.outputs[0].axis_assignment[0] == ("dcn", "data")

    rs = np.random.RandomState(0)
    c = rs.randn(10, 32) * 3
    y = rs.randint(0, 10, 1024)
    xs = (c[y] + rs.randn(1024, 32)).astype(np.float32)
    ff.fit(xs, y.reshape(-1, 1).astype(np.int32), epochs=2)
    assert ff.get_perf_metrics().get_accuracy() >= 0.85


def test_search_avoids_tp_across_dcn():
    """The cost model must keep `model`-axis traffic on ICI: a tp_col/tp_row
    pair prices its activation psum on `model` (ICI), and the same plan with
    the model axis over DCN would be far more expensive — sanity-check the
    pricing asymmetry that steers the search."""
    from flexflow_tpu.search.machine_model import CHIPS, TPUMachineModel

    ici = TPUMachineModel(CHIPS["v5p"], {"dcn": 2, "model": 4},
                          axis_over_dcn=frozenset({"dcn"}))
    bytes_ = 64 * 1024 * 1024
    assert ici.all_reduce(bytes_, "model") < ici.all_reduce(bytes_, "dcn")
