"""Native C++ PCG core vs pure-Python reference implementations (mirrors
the reference's tests/unit/test_dominators.cc fixtures)."""

import numpy as np
import pytest

from flexflow_tpu import native


@pytest.fixture(scope="module")
def lib_ok():
    if not native.available():
        pytest.skip("native core unavailable (no toolchain)")


def test_topo_order(lib_ok):
    # diamond: 0 -> {1,2} -> 3
    order = native.topo_order(4, [0, 0, 1, 2], [1, 2, 3, 3])
    assert order is not None
    pos = {v: i for i, v in enumerate(order)}
    assert pos[0] < pos[1] < pos[3] and pos[0] < pos[2] < pos[3]


def test_topo_cycle_detected(lib_ok):
    assert native.topo_order(2, [0, 1], [1, 0]) is None


def test_bottlenecks_diamond(lib_ok):
    # 0 -> {1,2} -> 3 -> 4 : bottlenecks are 0 and 3 (not 4, the sink)
    mask = native.bottlenecks(5, [0, 0, 1, 2, 3], [1, 2, 3, 3, 4])
    assert list(np.nonzero(mask)[0]) == [0, 3]


def test_transitive_reduction(lib_ok):
    # 0->1, 1->2, 0->2 : the shortcut 0->2 must drop
    keep = native.transitive_reduction(3, [0, 1, 0], [1, 2, 2])
    assert list(keep) == [True, True, False]


def test_idominators_multisource(lib_ok):
    # reference test_dominators.cc multisource fixture:
    # 0->2, 1->2, 2->3, 2->4, 3->5, 4->5
    idom = native.idominators(6, [0, 1, 2, 2, 3, 4], [2, 2, 3, 4, 5, 5])
    assert idom[0] == -1 and idom[1] == -1
    assert idom[2] == -1  # joined from two roots -> virtual root
    assert idom[3] == 2 and idom[4] == 2
    assert idom[5] == 2  # 3 and 4 intersect at 2


def test_bottlenecks_matches_python_on_real_graph(lib_ok):
    import sys

    sys.argv = ["test"]
    from flexflow_tpu import ActiMode, FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.search import CostModel, UnitySearch, machine_model_for_mesh

    config = FFConfig()
    ff = FFModel(config)
    x = ff.create_tensor((8, 16))
    a = ff.dense(x, 16, name="a")
    b1 = ff.dense(a, 16, name="b1")
    b2 = ff.relu(a, name="b2")
    c = ff.add(b1, b2, name="c")
    d = ff.dense(c, 4, name="d")
    ff.softmax(d, name="sm")
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    s = UnitySearch(ff.graph, ff.mesh, config,
                    CostModel(machine_model_for_mesh(ff.mesh)))
    native_names = {n.name for n in s.bottlenecks()}

    # force the Python fallback
    import flexflow_tpu.native as nat

    saved = nat._lib
    nat._lib = None
    nat._lib_tried = True
    try:
        py_names = {n.name for n in s.bottlenecks()}
    finally:
        nat._lib = saved
    assert native_names == py_names


def test_eval_makespan_chain(lib_ok):
    # chain 0->1->2: critical path = (1+0.5)+(2+0.5)+(3+0) = 7 > sum compute 6
    total = native.eval_makespan([1.0, 2.0, 3.0], [0.5, 0.5, 0.0],
                                 [0, 1], [1, 2])
    assert total == pytest.approx(7.0)


def test_eval_makespan_concurrent_branches(lib_ok):
    # diamond 0 -> {1,2} -> 3 (two-tower DLRM shape): comm-heavy branches
    # overlap, so makespan = max(sum compute, critical path), NOT the sum
    # of both branches' comm.
    compute = [1.0, 1.0, 1.0, 1.0]
    comm = [0.0, 5.0, 5.0, 0.0]
    total = native.eval_makespan(compute, comm, [0, 0, 1, 2], [1, 2, 3, 3])
    # critical path = 1 + (1+5) + 1 = 8; sum compute = 4
    assert total == pytest.approx(8.0)
    # pure-compute diamond: compute serializes (chips are shared) -> sum
    total = native.eval_makespan(compute, [0.0] * 4, [0, 0, 1, 2], [1, 2, 3, 3])
    assert total == pytest.approx(4.0)


def test_eval_makespan_cycle(lib_ok):
    with pytest.raises(ValueError, match="cycle"):
        native.eval_makespan([1.0, 1.0], [0.0, 0.0], [0, 1], [1, 0])
