"""ffsan tests: dtype-flow numerics verifier, NaN-provenance sanitizer,
SPMD divergence detector (analysis/numerics.py, analysis/spmd.py,
sanitize.py; docs/analysis.md "ffsan").

The acceptance matrix of ISSUE 10: injected-NaN localization per op
class (matmul / attention / layernorm / loss, fwd AND bwd, eager AND
--pipeline-steps 4), dtype-lattice unit tests for every finding code,
a clean-model zero-finding sweep, fingerprint-barrier mismatch
detection on a simulated 2-process run, and sanitizer-off bit-identity
with the uninstrumented step.
"""

import json
import math
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def _config(argv=()):
    sys.argv = ["test"] + list(argv)
    from flexflow_tpu import FFConfig

    config = FFConfig()
    config.batch_size = 4
    return config


def _compile(ff):
    from flexflow_tpu import LossType, SGDOptimizer

    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff


def _lm(config, seq=16):
    from flexflow_tpu import FFModel
    from flexflow_tpu.models import TransformerLMConfig, build_transformer_lm

    ff = FFModel(config)
    cfg = TransformerLMConfig(
        vocab_size=64, hidden_size=32, num_heads=2, num_layers=1,
        sequence_length=seq)
    build_transformer_lm(ff, cfg, batch_size=4)
    return ff, cfg


def _lm_data(cfg, n=16, seed=0):
    rs = np.random.RandomState(seed)
    X = {"tokens": rs.randint(0, cfg.vocab_size,
                              (n, cfg.sequence_length)).astype(np.int32),
         "positions": np.tile(np.arange(cfg.sequence_length,
                                        dtype=np.int32), (n, 1))}
    Y = rs.randint(0, cfg.vocab_size,
                   (n, cfg.sequence_length, 1)).astype(np.int32)
    return X, Y


def _reset_model(ff):
    """Re-derive pristine training state (the _compile_impl tail) so a
    NaN'd fit doesn't leak into the next test, and clear any fault."""
    ff.executor.set_numeric_fault(None)
    ff._rng = jax.random.key(ff.config.seed)
    ff._params, ff._state = ff.executor.init_variables(ff._rng)
    ff._opt_slots = ff.executor.place_update_sharded(
        ff.executor.replicate(ff.optimizer.init(ff._params)))
    if ff._state:
        ff._state = ff.executor.replicate(ff._state)
    ff._step = ff.executor.replicate(jnp.zeros((), jnp.int32))
    ff._counters = ff.executor.replicate(ff.metrics.zero_counters())


@pytest.fixture(scope="module")
def lm_bf16():
    """One sanitizer-on bf16 LM shared by the localization matrix (every
    test resets state + fault via _reset_model)."""
    from flexflow_tpu.fftype import DataType

    cfg = _config()
    cfg.mesh_axis_sizes = (2, 1, 1, 1)
    cfg.computation_dtype = DataType.DT_BFLOAT16
    cfg.sanitize_numerics = True
    ff, lmcfg = _lm(cfg)
    return _compile(ff), lmcfg


def _node_of(ff, op_type):
    from flexflow_tpu.fftype import OperatorType as OT

    return next(n.name for n in ff.graph.topo_order()
                if n.op_type == op_type)


def _target(ff, op_class: str) -> str:
    from flexflow_tpu.fftype import OperatorType as OT

    return {"matmul": lambda: _node_of(ff, OT.OP_LINEAR),
            "attention": lambda: _node_of(ff, OT.OP_MULTIHEAD_ATTENTION),
            "layernorm": lambda: _node_of(ff, OT.OP_LAYERNORM),
            "loss": lambda: "loss"}[op_class]()


# ============================== 1) injected-NaN localization matrix


@pytest.mark.parametrize("pipeline", [1, 4],
                         ids=["eager", "pipelined4"])
@pytest.mark.parametrize("phase", ["fwd", "bwd"])
@pytest.mark.parametrize("op_class",
                         ["matmul", "attention", "layernorm", "loss"])
def test_nan_localization(lm_bf16, op_class, phase, pipeline):
    from flexflow_tpu import sanitize

    ff, lmcfg = lm_bf16
    _reset_model(ff)
    target = _target(ff, op_class)
    fault_step = 2  # device-step numbering (0-based), mid-chunk for n=4
    ff.executor.set_numeric_fault(target, phase, fault_step)
    sanitize.get_monitor().reset()
    X, Y = _lm_data(lmcfg)
    ff.fit(X, Y, epochs=1, batch_size=4, shuffle=False, verbose=False,
           pipeline_steps=pipeline)
    jax.effects_barrier()
    info = sanitize.get_monitor().first_nonfinite()
    assert info is not None, (
        f"{op_class}/{phase}/pipeline={pipeline}: nothing localized")
    assert info["op"] == target, info
    assert info["phase"] == phase, info
    assert info["step"] == fault_step, info


def test_localization_clean_run_reports_nothing(lm_bf16):
    from flexflow_tpu import sanitize

    ff, lmcfg = lm_bf16
    _reset_model(ff)
    sanitize.get_monitor().reset()
    X, Y = _lm_data(lmcfg)
    ff.fit(X, Y, epochs=1, batch_size=4, shuffle=False, verbose=False)
    jax.effects_barrier()
    assert sanitize.get_monitor().first_nonfinite() is None


def test_fit_resets_stale_monitor_state(lm_bf16):
    """Same-process retry: a NaN'd fit must not leak its reports into
    the next fit's localization — fit starts a fresh provenance
    window."""
    from flexflow_tpu import sanitize

    ff, lmcfg = lm_bf16
    _reset_model(ff)
    target = _target(ff, "matmul")
    ff.executor.set_numeric_fault(target, "fwd", 0)
    X, Y = _lm_data(lmcfg)
    ff.fit(X, Y, epochs=1, batch_size=4, shuffle=False, verbose=False)
    jax.effects_barrier()
    assert sanitize.get_monitor().first_nonfinite() is not None
    # retry WITHOUT a manual monitor reset: the clean fit must see a
    # clean monitor
    _reset_model(ff)
    ff.fit(X, Y, epochs=1, batch_size=4, shuffle=False, verbose=False)
    jax.effects_barrier()
    assert sanitize.get_monitor().first_nonfinite() is None


def test_localize_prefers_stepped_events_over_stepless():
    """An interleaved eval NaN (step -1) must not outrank the training
    step the nan_loss alert is attributing; step-less events only win
    when nothing stepped exists."""
    from flexflow_tpu.sanitize import NumericsMonitor

    mon = NumericsMonitor()
    mon.report("eval_op", "fwd", 1, -1)
    mon.report("train_op", "fwd", 2, 5)
    info = mon.first_nonfinite()
    assert (info["op"], info["step"]) == ("train_op", 5)
    mon2 = NumericsMonitor()
    mon2.report("eval_op", "fwd", 1, -1)
    assert mon2.first_nonfinite()["op"] == "eval_op"


def test_localization_stepless_paths(lm_bf16):
    """eval/forward/decode run _apply without a step counter — probes
    report step -1 (the serving engine's serve.nonfinite check reads
    the same monitor)."""
    from flexflow_tpu import sanitize
    from flexflow_tpu.fftype import OperatorType as OT

    ff, lmcfg = lm_bf16
    _reset_model(ff)
    target = next(n.name for n in ff.graph.topo_order()
                  if n.op_type == OT.OP_LAYERNORM)
    ff.executor.set_numeric_fault(target, "fwd", 0)
    sanitize.get_monitor().reset()
    X, Y = _lm_data(lmcfg, n=4)
    ff.eval(X, Y, batch_size=4)
    jax.effects_barrier()
    info = sanitize.get_monitor().first_nonfinite()
    assert info is not None
    assert (info["op"], info["phase"], info["step"]) == \
        (target, "fwd", -1)


# ============================== 2) dtype-lattice unit tests


def _pt(shape, dtype):
    from flexflow_tpu.tensor import ParallelTensor, ParallelTensorShape

    return ParallelTensor(ParallelTensorShape.from_shape(shape, dtype))


def _chain(*nodes_and_outputs):
    """Build a linear graph from (op_type, params, name, out_shape,
    out_dtype) tuples."""
    from flexflow_tpu.pcg.graph import Graph, OpNode

    g = Graph()
    prev = None
    for op_type, params, name, shape, dtype in nodes_and_outputs:
        node = g.add_node(OpNode(op_type, params, name=name))
        node.outputs = [_pt(shape, dtype)]
        if prev is not None:
            node.inputs = [prev.outputs[0]]
            g.add_edge(prev, node)
        prev = node
    return g


@pytest.fixture
def mesh8():
    from flexflow_tpu.machine import MeshShape, build_mesh

    return build_mesh(MeshShape((2, 4, 1, 1),
                                ("data", "model", "pipe", "seq")))


def _codes(findings):
    return [f.code for f in findings]


def test_lattice_parallel_dtype_mismatch(mesh8):
    from flexflow_tpu.analysis import numerics
    from flexflow_tpu.fftype import DataType, OperatorType as OT
    from flexflow_tpu.parallel.ops import CombineParams

    g = _chain(
        (OT.OP_INPUT, None, "x", (8, 8), DataType.DT_BFLOAT16),
        (OT.OP_COMBINE, CombineParams(0, 2), "combine", (8, 8),
         DataType.DT_FLOAT))
    findings = numerics.run(g, mesh8, None)
    assert "parallel_dtype_mismatch" in _codes(findings)
    f = next(x for x in findings if x.code == "parallel_dtype_mismatch")
    assert f.severity == "error"


def test_lattice_low_precision_accum_reduce(mesh8):
    from flexflow_tpu.analysis import numerics
    from flexflow_tpu.fftype import DataType, OperatorType as OT
    from flexflow_tpu.ops import ReduceParams

    g = _chain(
        (OT.OP_INPUT, None, "x", (64, 1024), DataType.DT_BFLOAT16),
        (OT.OP_REDUCE_SUM, ReduceParams(OT.OP_REDUCE_SUM, (0, 1)),
         "big_sum", (1,), DataType.DT_BFLOAT16))
    assert "low_precision_accum" in _codes(numerics.run(g, mesh8, None))
    # a small reduce stays silent (threshold = ACCUM_ELEMS_WARN)
    g2 = _chain(
        (OT.OP_INPUT, None, "x", (4, 4), DataType.DT_BFLOAT16),
        (OT.OP_REDUCE_SUM, ReduceParams(OT.OP_REDUCE_SUM, (0, 1)),
         "small_sum", (1,), DataType.DT_BFLOAT16))
    assert "low_precision_accum" not in _codes(
        numerics.run(g2, mesh8, None))
    # f32 reduces of any size stay silent
    g3 = _chain(
        (OT.OP_INPUT, None, "x", (64, 1024), DataType.DT_FLOAT),
        (OT.OP_REDUCE_SUM, ReduceParams(OT.OP_REDUCE_SUM, (0, 1)),
         "f32_sum", (1,), DataType.DT_FLOAT))
    assert "low_precision_accum" not in _codes(
        numerics.run(g3, mesh8, None))


def test_lattice_low_precision_accum_reduction_partial_sums(mesh8):
    from flexflow_tpu.analysis import numerics
    from flexflow_tpu.fftype import DataType, OperatorType as OT
    from flexflow_tpu.parallel.ops import ReductionParams

    g = _chain(
        (OT.OP_INPUT, None, "x", (8, 8), DataType.DT_BFLOAT16),
        (OT.OP_REDUCTION, ReductionParams(64), "wide_psum", (8, 8),
         DataType.DT_BFLOAT16))
    assert "low_precision_accum" in _codes(numerics.run(g, mesh8, None))
    # a narrow partial sum (degree < ACCUM_TERMS_WARN) stays silent
    g2 = _chain(
        (OT.OP_INPUT, None, "x", (8, 8), DataType.DT_BFLOAT16),
        (OT.OP_REDUCTION, ReductionParams(4), "narrow_psum", (8, 8),
         DataType.DT_BFLOAT16))
    assert "low_precision_accum" not in _codes(
        numerics.run(g2, mesh8, None))


def test_lattice_low_precision_grad_reduce_scatter(mesh8):
    from jax.sharding import PartitionSpec

    from flexflow_tpu.analysis import AnalysisContext, numerics
    from flexflow_tpu.fftype import DataType, OperatorType as OT
    from flexflow_tpu.ops.base import WeightSpec
    from flexflow_tpu.pcg.graph import Graph, OpNode

    g = Graph()
    node = g.add_node(OpNode(OT.OP_LINEAR, None, name="fc"))
    node.outputs = [_pt((8, 8), DataType.DT_FLOAT)]
    node.weight_specs = [WeightSpec("kernel", (8, 8),
                                    DataType.DT_BFLOAT16)]
    ctx = AnalysisContext(update_specs={
        ("fc", "kernel"): (PartitionSpec("data"), (8, 8))})
    findings = numerics.run(g, mesh8, ctx)
    f = next(x for x in findings if x.code == "low_precision_accum")
    assert "reduce-scatter" in f.message


def test_lattice_master_bypass(mesh8):
    from flexflow_tpu.analysis import AnalysisContext, numerics
    from flexflow_tpu.fftype import DataType, OperatorType as OT
    from flexflow_tpu.ops.base import WeightSpec
    from flexflow_tpu.pcg.graph import Graph, OpNode

    g = Graph()
    node = g.add_node(OpNode(OT.OP_LINEAR, None, name="fc"))
    node.outputs = [_pt((8, 8), DataType.DT_FLOAT)]
    node.weight_specs = [WeightSpec("kernel", (8, 8),
                                    DataType.DT_BFLOAT16)]
    cfg = _config()
    cfg.computation_dtype = DataType.DT_BFLOAT16
    findings = numerics.run(g, mesh8,
                            AnalysisContext(config=cfg, training=True))
    f = next(x for x in findings if x.code == "master_bypass")
    assert f.severity == "error"
    # inference compiles carry no master-weight invariant
    assert "master_bypass" not in _codes(numerics.run(
        g, mesh8, AnalysisContext(config=cfg, training=False)))
    # fp32 weights under the same policy are the correct master path
    node.weight_specs = [WeightSpec("kernel", (8, 8),
                                    DataType.DT_FLOAT)]
    assert "master_bypass" not in _codes(numerics.run(
        g, mesh8, AnalysisContext(config=cfg, training=True)))


def test_lattice_downcast_roundtrip(mesh8):
    from flexflow_tpu.analysis import numerics
    from flexflow_tpu.fftype import DataType, OperatorType as OT
    from flexflow_tpu.ops import CastParams, ReshapeParams

    g = _chain(
        (OT.OP_INPUT, None, "x", (8, 8), DataType.DT_FLOAT),
        (OT.OP_CAST, CastParams(DataType.DT_BFLOAT16), "down", (8, 8),
         DataType.DT_BFLOAT16),
        (OT.OP_RESHAPE, ReshapeParams((64,)), "view", (64,),
         DataType.DT_BFLOAT16),
        (OT.OP_CAST, CastParams(DataType.DT_FLOAT), "up", (64,),
         DataType.DT_FLOAT))
    f = next(x for x in numerics.run(g, mesh8, None)
             if x.code == "downcast_roundtrip")
    assert f.details["upcast_at"] == "up"


def test_lattice_clean_graph_single_info(mesh8):
    from flexflow_tpu.analysis import numerics
    from flexflow_tpu.fftype import DataType, OperatorType as OT

    g = _chain((OT.OP_INPUT, None, "x", (8, 8), DataType.DT_FLOAT),
               (OT.OP_RELU, None, "act", (8, 8), DataType.DT_FLOAT))
    findings = numerics.run(g, mesh8, None)
    assert _codes(findings) == ["numerics_clean"]
    assert findings[0].severity == "info"


# ============================== 3) clean-model zero-finding sweep


def test_clean_sweep_bf16_lm(lm_bf16):
    ff, _ = lm_bf16
    res = ff._analysis
    assert res is not None
    assert {"dtype_flow", "spmd_uniformity"} <= set(res.passes_run)
    ffsan = [f for f in res.findings
             if f.pass_name in ("dtype_flow", "spmd_uniformity")]
    assert ffsan, "ffsan passes reported nothing at all"
    assert all(f.severity == "info" for f in ffsan), [
        str(f) for f in ffsan if f.severity != "info"]


def test_clean_sweep_fp32_searched():
    ff, _ = _lm(_config(["--mesh", "2,4,1,1", "--budget", "4",
                         "--enable-parameter-parallel"]))
    _compile(ff)
    ffsan = [f for f in ff._analysis.findings
             if f.pass_name in ("dtype_flow", "spmd_uniformity")]
    assert all(f.severity == "info" for f in ffsan), [
        str(f) for f in ffsan if f.severity != "info"]


# ============================== 4) lint rules


def _lint(src, select):
    from flexflow_tpu.analysis import lint

    return [f.code for f in lint.lint_source(src, "snippet.py",
                                             select=select)]


def test_lint_low_precision_accum():
    bad = """
def f(x):
    import jax.numpy as jnp
    return jnp.sum(x.astype(jnp.bfloat16))
"""
    assert _lint(bad, ("low_precision_accum",)) == \
        ["low_precision_accum"]
    bad_kw = """
def f(x):
    import jax.numpy as jnp
    return jnp.mean(x, dtype=jnp.float16)
"""
    assert _lint(bad_kw, ("low_precision_accum",)) == \
        ["low_precision_accum"]
    # f32-accumulate-then-downcast (the codebase convention) is clean
    good = """
def f(x):
    import jax.numpy as jnp
    return jnp.sum(x.astype(jnp.float32)).astype(jnp.bfloat16)
"""
    assert _lint(good, ("low_precision_accum",)) == []
    # order statistics carry no accumulation error
    assert _lint("""
def f(x):
    import jax.numpy as jnp
    return jnp.max(x.astype(jnp.bfloat16))
""", ("low_precision_accum",)) == []


def test_lint_host_divergent_branch():
    deadlock = """
def f(payload):
    import time
    if time.perf_counter() > 100.0:
        barrier("resync")
"""
    found = _lint(deadlock, ("host_divergent_branch",))
    assert found == ["host_divergent_branch"]
    divergent_trace = """
def f(fn):
    import os
    if os.getenv("FAST"):
        return jit(fn)
    return fn
"""
    assert _lint(divergent_trace, ("host_divergent_branch",)) == \
        ["host_divergent_branch"]
    # the sanctioned idiom: decide via broadcast state, not local time
    good = """
def f(fn, decision):
    if decision["recompile"]:
        return jit(fn)
    return fn
"""
    assert _lint(good, ("host_divergent_branch",)) == []


def test_lint_repo_clean_for_new_rules():
    """The CI invariant: the repo itself carries no unsuppressed
    low_precision_accum / host_divergent_branch findings."""
    import os

    from flexflow_tpu.analysis import lint

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = lint.lint_paths(
        [os.path.join(root, "flexflow_tpu"),
         os.path.join(root, "scripts")],
        select=("low_precision_accum", "host_divergent_branch"))
    assert findings == [], [str(f) for f in findings]


# ============================== 5) fingerprint barrier (simulated fleet)


def test_fingerprint_barrier_lockstep_and_mismatch(lm_bf16):
    from flexflow_tpu.analysis import spmd

    ff, _ = lm_bf16
    # single-process short-circuit (the default channel)
    v = spmd.fingerprint_barrier(ff)
    assert v["status"] == "single_process"
    # simulated 2-process lockstep: the coordinator's payload comes back
    # unchanged over the injected broadcast channel
    v = spmd.fingerprint_barrier(ff, broadcast=lambda p: p)
    assert v["status"] == "ok"
    assert v["fingerprint"] == spmd.step_fingerprint(ff)
    # simulated divergent second process
    with pytest.raises(spmd.SPMDDivergenceError) as ei:
        spmd.fingerprint_barrier(
            ff, broadcast=lambda p: {
                "fingerprint": "divergent",
                "payload": dict(spmd.fingerprint_payload(ff),
                                numerics="divergent")})
    assert "numerics" in str(ei.value)


def test_fingerprint_barrier_peer_mismatch_aborts_in_lockstep(lm_bf16):
    """The lockstep half: a process whose OWN fingerprint matches the
    coordinator must still abort when the gathered flags show a peer
    diverged — otherwise the survivors hang in the next collective."""
    from flexflow_tpu.analysis import spmd

    ff, _ = lm_bf16
    with pytest.raises(spmd.SPMDDivergenceError) as ei:
        spmd.fingerprint_barrier(ff, broadcast=lambda p: p,
                                 gather=lambda m: [m, False])
    assert ei.value.peer_mismatch
    # an all-matching fleet passes through the same two-phase path
    v = spmd.fingerprint_barrier(ff, broadcast=lambda p: p,
                                 gather=lambda m: [m, True])
    assert v["status"] == "ok"


def test_fingerprint_tracks_numerics_policy(lm_bf16):
    from flexflow_tpu.analysis import spmd

    ff, _ = lm_bf16
    fp = spmd.step_fingerprint(ff)
    assert fp == spmd.step_fingerprint(ff)  # deterministic
    saved = ff.config.sanitize_numerics
    ff.config.sanitize_numerics = not saved
    try:
        assert spmd.step_fingerprint(ff) != fp
    finally:
        ff.config.sanitize_numerics = saved


# ============================== 6) alert enrichment (fire-once kept)


def test_nan_loss_rule_enriched_and_fire_once():
    from flexflow_tpu.diagnostics.health import NaNLossRule

    rule = NaNLossRule()
    alert = rule.check({"step": 7, "loss": float("nan"),
                        "nonfinite_op": "l0_attn",
                        "nonfinite_phase": "bwd",
                        "nonfinite_step": 6})
    assert alert is not None
    assert "l0_attn" in alert.message and "backward" in alert.message
    assert alert.details == {"op": "l0_attn", "phase": "bwd",
                             "at_step": 6}
    rec = alert.to_record()
    assert rec["details"]["op"] == "l0_attn"
    # fire-once: the dead run gets ONE alert
    assert rule.check({"step": 8, "loss": float("nan")}) is None


def test_nan_loss_rule_unenriched_without_sanitizer():
    from flexflow_tpu.diagnostics.health import NaNLossRule

    alert = NaNLossRule().check({"step": 3, "loss": float("inf")})
    assert alert is not None
    assert alert.details == {}
    assert "first non-finite" not in alert.message


def test_alerts_jsonl_names_op_end_to_end(tmp_path):
    """Satellite 1 end-to-end: --sanitize-numerics + diagnostics → the
    nan_loss record in alerts.jsonl carries the localization."""
    from flexflow_tpu import FFModel
    from flexflow_tpu.fftype import OperatorType as OT

    cfg = _config()
    cfg.sanitize_numerics = True
    ff, lmcfg = _lm(cfg)
    _compile(ff)
    ff.enable_diagnostics(str(tmp_path))
    target = next(n.name for n in ff.graph.topo_order()
                  if n.op_type == OT.OP_LINEAR)
    ff.executor.set_numeric_fault(target, "bwd", 1)
    from flexflow_tpu import sanitize

    sanitize.get_monitor().reset()
    X, Y = _lm_data(lmcfg)
    ff.fit(X, Y, epochs=1, batch_size=4, shuffle=False, verbose=False)
    alerts = [json.loads(line)
              for line in open(tmp_path / "alerts.jsonl")
              if line.strip()]
    nan = [a for a in alerts if a.get("rule") == "nan_loss"]
    assert len(nan) == 1
    assert nan[0]["details"] == {"op": target, "phase": "bwd",
                                 "at_step": 1}


# ============================== 7) sanitizer-off bit-identity


def _mlp(sanitize_on: bool):
    from flexflow_tpu import ActiMode, FFModel

    cfg = _config()
    cfg.batch_size = 8
    cfg.sanitize_numerics = sanitize_on
    ff = FFModel(cfg)
    x = ff.create_tensor((8, 16), name="input_0")
    t = ff.dense(x, 32, ActiMode.AC_MODE_RELU, name="fc1")
    ff.softmax(ff.dense(t, 8, name="head"), name="sm")
    return _compile(ff)


def _fit_and_flatten(ff, rng):
    X = rng.randn(32, 16).astype(np.float32)
    Y = rng.randint(0, 8, (32, 1)).astype(np.int32)
    ff.fit(X, Y, epochs=1, batch_size=8, shuffle=False, verbose=False)
    return jax.tree_util.tree_leaves(jax.device_get(ff._params))


def test_sanitizer_off_and_on_bit_identical():
    """Off: the traced step is the uninstrumented one (HEAD behavior).
    On: the probes are effectful identities — the training trajectory
    stays BIT-identical, so the flag can be flipped on a production run
    without changing its math."""
    base = _fit_and_flatten(_mlp(False), np.random.RandomState(0))
    off2 = _fit_and_flatten(_mlp(False), np.random.RandomState(0))
    on = _fit_and_flatten(_mlp(True), np.random.RandomState(0))
    for a, b in zip(base, off2):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(base, on):
        assert np.array_equal(np.asarray(a), np.asarray(b))


# ============================== 8) report & doctor surface


def test_strategy_report_carries_ffsan_fields(tmp_path):
    cfg = _config()
    cfg.sanitize_numerics = True
    cfg.spmd_barrier = True
    ff, _ = _lm(cfg)
    _compile(ff)
    ff.enable_diagnostics(str(tmp_path))
    ff.get_diagnostics().on_compile()
    rep = json.load(open(tmp_path / "strategy_report.json"))
    assert rep["sanitize_numerics"] is True
    assert rep["spmd_barrier"] == "single_process"
    assert {"dtype_flow", "spmd_uniformity"} <= set(
        rep["analysis"]["passes_run"])


def test_dtype_flow_warm_under_budget(lm_bf16):
    """Acceptance: the static numerics pass adds <5 ms to a warm
    compile (source scans cached per process, pure graph walk)."""
    import time

    from flexflow_tpu.analysis import context_for_model, numerics

    ff, _ = lm_bf16
    ctx = context_for_model(ff)
    numerics.run(ff.graph, ff.mesh, ctx)  # warm any lazy imports
    best = math.inf
    for _ in range(3):
        t0 = time.perf_counter()
        numerics.run(ff.graph, ff.mesh, ctx)
        best = min(best, time.perf_counter() - t0)
    assert best < 0.005, f"dtype_flow warm pass took {best * 1e3:.2f} ms"
