"""Parallelism tests on the virtual 8-device CPU mesh.

Covers the four parallel ops' IR shape transforms (reference
src/parallel_ops/*), megatron-style tensor parallelism end-to-end (the
create_replicate_linear_combine substitution family, substitution.cc:71-96),
and ring attention (sequence parallelism the reference lacks, SURVEY §5).
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P


def test_parallel_op_shape_transforms():
    from flexflow_tpu.fftype import DataType, OperatorType as OT
    from flexflow_tpu.parallel import (
        CombineParams,
        ReductionParams,
        RepartitionParams,
        ReplicateParams,
        apply_parallel_op_shape,
    )
    from flexflow_tpu.tensor import ParallelTensorShape

    s = ParallelTensorShape.from_shape((64, 32), DataType.DT_FLOAT)
    s2 = apply_parallel_op_shape(s, OT.OP_REPARTITION, RepartitionParams(0, 4))
    assert s2.dims[0].degree == 4 and s2.dims[0].size == 64
    s3 = apply_parallel_op_shape(s2, OT.OP_COMBINE, CombineParams(0, 2))
    assert s3.dims[0].degree == 2
    s4 = apply_parallel_op_shape(s3, OT.OP_REPLICATE, ReplicateParams(4))
    assert s4.num_replica_dims == 1 and s4.total_degree == 8
    s5 = apply_parallel_op_shape(s4, OT.OP_REDUCTION, ReductionParams(4))
    assert s5.num_replica_dims == 0 and s5.dims[0].degree == 2
    # logical shape is invariant under all four
    assert s5.logical_shape == s.logical_shape


def _build_tp_mlp(mesh_axes, batch=32, in_dim=64, hidden=128, out=10,
                  strategy=None):
    sys.argv = ["test"]
    from flexflow_tpu import ActiMode, FFConfig, FFModel, LossType, SGDOptimizer

    config = FFConfig()
    config.mesh_axis_sizes = mesh_axes
    ff = FFModel(config)
    x = ff.create_tensor((batch, in_dim))
    t = ff.dense(x, hidden, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, out, name="fc2")
    t = ff.softmax(t, name="sm")
    if strategy is not None:
        ff.set_strategy(strategy(ff))
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff


def test_megatron_tp_matches_single_device():
    """TP(model=4) × DP(data=2) must produce numerically equal training to
    the unsharded run (same seed → same init → same updates)."""
    from flexflow_tpu.parallel import megatron_transformer

    rs = np.random.RandomState(0)
    x = rs.randn(64, 64).astype(np.float32)
    y = rs.randint(0, 10, (64, 1)).astype(np.int32)

    ff_ref = _build_tp_mlp((1, 1, 1, 1))
    ff_tp = _build_tp_mlp((2, 4, 1, 1), strategy=megatron_transformer)

    # verify the strategy actually sharded fc1's kernel over `model`
    k1 = ff_tp._params["fc1"]["kernel"]
    assert k1.sharding.spec == P(None, "model"), k1.sharding

    for ff in (ff_ref, ff_tp):
        ff.fit(x, y, epochs=2, batch_size=32, shuffle=False)

    for lname in ("fc1", "fc2"):
        for wname in ("kernel", "bias"):
            a = ff_ref.get_weight(lname, wname)
            b = ff_tp.get_weight(lname, wname)
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_ring_attention_matches_reference():
    from flexflow_tpu.machine import MeshShape, build_mesh
    from flexflow_tpu.ops.attention import sdpa_xla
    from flexflow_tpu.parallel.ring_attention import ring_attention

    mesh = build_mesh(MeshShape((2, 1, 4, 1), ("data", "model", "seq", "pipe")))
    rs = np.random.RandomState(1)
    b, h, s, d = 4, 2, 32, 8
    q = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)

    for causal in (False, True):
        expected = sdpa_xla(q, k, v, causal=causal, scale=0.25)
        got = jax.jit(
            lambda q, k, v: ring_attention(
                q, k, v, causal=causal, scale=0.25, mesh=mesh
            )
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=2e-5, atol=2e-5)


def test_ring_attention_grads_match():
    from flexflow_tpu.machine import MeshShape, build_mesh
    from flexflow_tpu.ops.attention import sdpa_xla
    from flexflow_tpu.parallel.ring_attention import ring_attention

    mesh = build_mesh(MeshShape((1, 1, 4, 1), ("data", "model", "seq", "pipe")))
    rs = np.random.RandomState(2)
    b, h, s, d = 2, 2, 16, 4
    q = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    k = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)
    v = jnp.asarray(rs.randn(b, h, s, d), jnp.float32)

    def loss_ring(q, k, v):
        return jnp.sum(
            ring_attention(q, k, v, causal=True, scale=0.5, mesh=mesh) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(sdpa_xla(q, k, v, causal=True, scale=0.5) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)


def test_expert_parallel_fused_moe():
    """Fused Experts op trains under expert-axis sharding and matches the
    unsharded run."""
    sys.argv = ["test"]
    from flexflow_tpu import ActiMode, FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.parallel import expert_parallel_moe

    def build(mesh_axes, use_strategy):
        config = FFConfig()
        config.mesh_axis_sizes = mesh_axes
        ff = FFModel(config)
        x = ff.create_tensor((32, 16))
        from flexflow_tpu import ActiMode as AM

        gate = ff.dense(x, 4, AM.AC_MODE_RELU, name="gate")
        probs = ff.softmax(gate, name="gate_sm")
        topk_v, topk_i = ff.top_k(probs, 2, name="topk")
        t = ff.experts(x, topk_v, topk_i, num_experts=4, hidden_size=16,
                       alpha=2.0, lambda_bal=0.01, name="experts")
        t = ff.dense(t, 8, name="head")
        t = ff.softmax(t, name="sm")
        if use_strategy:
            ff.set_strategy(expert_parallel_moe(ff))
        ff.compile(optimizer=SGDOptimizer(lr=0.05),
                   loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
        return ff

    rs = np.random.RandomState(3)
    x = rs.randn(64, 16).astype(np.float32)
    y = rs.randint(0, 8, (64, 1)).astype(np.int32)

    ff_ref = build((1, 1, 1, 1), False)
    ff_ep = build((2, 4, 1, 1), True)
    for ff in (ff_ref, ff_ep):
        ff.fit(x, y, epochs=1, batch_size=32, shuffle=False)

    def stacked_kernel(ff):
        for ws in ff._params.values():
            if "kernel" in ws and ws["kernel"].ndim == 3:
                return np.asarray(ws["kernel"])
        raise AssertionError("no stacked experts kernel found")

    np.testing.assert_allclose(stacked_kernel(ff_ref), stacked_kernel(ff_ep),
                               rtol=2e-4, atol=2e-5)


def test_explicit_parallel_op_builders_reshard():
    """repartition/combine builders must actually change the runtime
    sharding of the tensor flowing through them."""
    sys.argv = ["test"]
    from flexflow_tpu import ActiMode, FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.fftype import OperatorType as OT

    config = FFConfig()
    config.mesh_axis_sizes = (2, 4, 1, 1)
    ff = FFModel(config)
    x = ff.create_tensor((32, 64))
    t = ff.dense(x, 64, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.repartition(t, dim=1, degree=4, name="rp")   # shard feature dim
    t = ff.combine(t, dim=1, degree=4, name="cb")       # unshard it again
    t = ff.dense(t, 10, name="fc2")
    t = ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    rp = next(n for n in ff.graph.topo_order() if n.name == "rp")
    cb = next(n for n in ff.graph.topo_order() if n.name == "cb")
    assert rp.outputs[0].partition_spec() == P("data", "model")
    assert cb.outputs[0].partition_spec() == P("data")

    rs = np.random.RandomState(0)
    x_arr = rs.randn(32, 64).astype(np.float32)
    y_arr = rs.randint(0, 10, (32, 1)).astype(np.int32)
    ff.fit(x_arr, y_arr, epochs=1, batch_size=32)  # runs without error
