"""Pipeline-parallelism tests: the ppermute fill/drain schedule
(parallel/pipeline.py + the OP_PIPE_BLOCKS op) must match the sequential
stack exactly — forward AND gradients — and train end-to-end on a
(data × pipe) mesh. The reference's OP_PIPELINE is an unimplemented enum
(ffconst.h:159); these tests certify the capability that exceeds it."""

import sys

import numpy as np
import pytest


def _config(mesh_axes, batch=8):
    sys.argv = ["test"]
    from flexflow_tpu import FFConfig

    config = FFConfig()
    config.mesh_axis_sizes = mesh_axes
    config.batch_size = batch
    return config


def test_pipeline_apply_matches_sequential():
    """Raw schedule check: pipelined forward and grads == sequential scan."""
    import jax
    import jax.numpy as jnp

    from flexflow_tpu.machine import build_mesh, MeshShape
    from flexflow_tpu.parallel.pipeline import pipeline_apply, _sequential

    rs = np.random.RandomState(0)
    L, b, d = 4, 8, 16
    stacked = {
        "w": jnp.asarray(rs.randn(L, d, d) * 0.1, jnp.float32),
        "b": jnp.asarray(rs.randn(L, d) * 0.1, jnp.float32),
    }
    x = jnp.asarray(rs.randn(b, d), jnp.float32)

    def block(w, a):
        return jnp.tanh(a @ w["w"] + w["b"])

    mesh = build_mesh(MeshShape((2, 1, 4, 1)))  # data=2, pipe=4

    def loss_seq(s, x):
        return jnp.sum(_sequential(s, x, block) ** 2)

    def loss_pipe(s, x):
        return jnp.sum(pipeline_apply(
            s, x, block, mesh=mesh, num_microbatches=4) ** 2)

    with mesh:
        y_seq = jax.jit(lambda s, x: _sequential(s, x, block))(stacked, x)
        y_pipe = jax.jit(lambda s, x: pipeline_apply(
            s, x, block, mesh=mesh, num_microbatches=4))(stacked, x)
        np.testing.assert_allclose(np.asarray(y_pipe), np.asarray(y_seq),
                                   rtol=1e-6, atol=1e-6)
        g_seq = jax.jit(jax.grad(loss_seq))(stacked, x)
        g_pipe = jax.jit(jax.grad(loss_pipe))(stacked, x)
        for k in stacked:
            np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                       np.asarray(g_seq[k]),
                                       rtol=1e-5, atol=1e-5)


def test_pipeline_rejects_indivisible_layers():
    import jax.numpy as jnp

    from flexflow_tpu.machine import build_mesh, MeshShape
    from flexflow_tpu.parallel.pipeline import pipeline_apply

    mesh = build_mesh(MeshShape((1, 1, 4, 1)))
    stacked = {"w": jnp.zeros((3, 4, 4))}  # 3 layers, 4 stages
    with pytest.raises(ValueError, match="pipeline"):
        pipeline_apply(stacked, jnp.zeros((4, 4)), lambda w, a: a,
                       mesh=mesh)


def _logits_of(mesh_axes, batch=4):
    import jax

    from flexflow_tpu import FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import (
        TransformerLMConfig, build_transformer_lm_pipelined,
    )

    config = _config(mesh_axes, batch=batch)
    ff = FFModel(config)
    c = TransformerLMConfig(vocab_size=64, hidden_size=32, num_heads=2,
                            num_layers=4, sequence_length=16,
                            attention_impl="xla")
    build_transformer_lm_pipelined(ff, c, batch_size=batch,
                                   num_microbatches=2)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    rs = np.random.RandomState(0)
    toks = rs.randint(0, c.vocab_size, (batch, 16)).astype(np.int32)
    pos = np.tile(np.arange(16, dtype=np.int32), (batch, 1))
    fwd = ff.executor.build_forward()
    xs = ff.executor.shard_batch(
        {"tokens": toks, "positions": pos},
        {n.name: n.outputs[0].partition_spec()
         for n in ff.graph.sources()})
    logits, _ = fwd(ff._params, ff._state, xs, False)
    return np.asarray(jax.device_get(logits)), ff, c, toks, pos


def test_two_stage_lm_matches_single_device():
    """The pp=2 LM's logits equal the same model on a 1-device mesh (same
    seeds → same init → same function)."""
    single, *_ = _logits_of((1, 1, 1, 1))
    piped, *_ = _logits_of((2, 1, 2, 1))  # data=2 × pipe=2
    np.testing.assert_allclose(piped, single, rtol=2e-5, atol=2e-5)


def test_pipelined_lm_trains():
    """End-to-end fit on the (data × pipe) mesh: loss decreases."""
    from flexflow_tpu import FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import (
        TransformerLMConfig, build_transformer_lm_pipelined,
    )

    batch = 8
    config = _config((2, 1, 2, 1), batch=batch)
    ff = FFModel(config)
    c = TransformerLMConfig(vocab_size=64, hidden_size=32, num_heads=2,
                            num_layers=4, sequence_length=16,
                            attention_impl="xla")
    build_transformer_lm_pipelined(ff, c, batch_size=batch,
                                   num_microbatches=2)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    rs = np.random.RandomState(0)
    toks = rs.randint(0, c.vocab_size, (batch, 16)).astype(np.int32)
    pos = np.tile(np.arange(16, dtype=np.int32), (batch, 1))
    labels = rs.randint(0, c.vocab_size, (batch, 16, 1)).astype(np.int32)
    bd = ff._make_batch({"tokens": toks, "positions": pos}, labels)
    step = ff.executor.build_train_step()
    import jax

    state = (ff._params, ff._state, ff._opt_slots, ff._step, ff._counters)
    losses = []
    for i in range(8):
        out = step(*state, jax.random.key(i), bd)
        state = out[:5]
        losses.append(float(out[-1]))
    assert losses[-1] < losses[0], losses
