"""Searchable pipeline parallelism: the pipe axis participates in the
search (cost model gains a fill/drain bubble term and ppermute hop
pricing; the mesh factorization search arbitrates dp-vs-pp where each
candidate's costing matches its execution). EXCEEDS the reference, whose
OP_PIPELINE is an enum with no implementation (ffconst.h:159)."""

import sys

import numpy as np
import pytest


def _config(mesh_axes, batch=16, argv=()):
    sys.argv = ["test"] + list(argv)
    from flexflow_tpu import FFConfig

    config = FFConfig()
    config.mesh_axis_sizes = mesh_axes
    config.batch_size = batch
    return config


def _stack_graph(config, batch, L=16, d=256, s=64, heads=4):
    from test_joint_search import _pcg_of

    from flexflow_tpu import FFModel

    ff = FFModel(config)
    x = ff.create_tensor((batch, s, d), name="x")
    ff.pipeline_blocks(x, L, heads, name="stack")
    return _pcg_of(ff)


def test_pp_is_sole_config_on_pipe_mesh():
    """On a pipe-carrying mesh the runtime pipelines unconditionally
    (parallel/pipeline.py keys off the mesh), so costing must match:
    PIPE_BLOCKS gets exactly the pp config, weights sharded over pipe."""
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.machine_model import CHIPS, TPUMachineModel
    from flexflow_tpu.search.mesh_search import MeshSpec
    from flexflow_tpu.search.unity import UnitySearch

    config = _config((2, 1, 2, 1))
    mesh = MeshSpec({"data": 2, "model": 1, "pipe": 2, "seq": 1})
    g = _stack_graph(config, batch=16)
    us = UnitySearch(g, mesh, config,
                     CostModel(TPUMachineModel(CHIPS["v5e"],
                                               dict(mesh.shape))))
    stack = next(n for n in g.topo_order() if n.name == "stack")
    cfgs = us.node_configs(stack)
    assert [c.name for c in cfgs] == ["pp"]
    assert all("pipe" in str(spec) for _, spec in cfgs[0].weight_specs)
    # and without a pipe axis: plain dp
    mesh1 = MeshSpec({"data": 4, "model": 1, "pipe": 1, "seq": 1})
    us1 = UnitySearch(g, mesh1, config,
                      CostModel(TPUMachineModel(CHIPS["v5e"],
                                                dict(mesh1.shape))))
    assert [c.name for c in us1.node_configs(stack)] == ["dp"]


def test_pp_nondivisible_layer_count_rejected():
    """L % P != 0 would raise at dispatch (pipeline_apply); the search must
    prune such a mesh candidate at costing."""
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.machine_model import CHIPS, TPUMachineModel
    from flexflow_tpu.search.mesh_search import MeshSpec
    from flexflow_tpu.search.unity import UnitySearch

    config = _config((1, 1, 8, 1))
    g = _stack_graph(config, batch=16, L=4)  # 4 % 8 != 0
    mesh = MeshSpec({"data": 1, "model": 1, "pipe": 8, "seq": 1})
    us = UnitySearch(g, mesh, config,
                     CostModel(TPUMachineModel(CHIPS["v5e"],
                                               dict(mesh.shape))))
    stack = next(n for n in g.topo_order() if n.name == "stack")
    with pytest.raises(ValueError, match="do not divide"):
        us.node_configs(stack)


def test_pp_cost_between_ideal_and_sequential():
    """The bubble term: pp on P=2 (default M=2P=4) must price ABOVE the
    ideal T/2 (fill/drain placeholder compute is real) and BELOW the
    sequential T (pipelining still wins at these shapes)."""
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.machine_model import CHIPS, TPUMachineModel
    from flexflow_tpu.search.mesh_search import MeshSpec
    from flexflow_tpu.search.unity import UnitySearch

    def stack_cost(pipe):
        config = _config((1, 1, pipe, 1))
        g = _stack_graph(config, batch=16)
        mesh = MeshSpec({"data": 1, "model": 1, "pipe": pipe, "seq": 1})
        us = UnitySearch(g, mesh, config,
                         CostModel(TPUMachineModel(CHIPS["v5e"],
                                                   dict(mesh.shape))))
        stack = next(n for n in g.topo_order() if n.name == "stack")
        cfg = us.node_configs(stack)[0]
        t, _ = us.evaluate({stack.guid: cfg})
        return t

    t_seq = stack_cost(1)
    t_pp = stack_cost(2)
    # bubble (M+P-1)/M = 1.25 at P=2, M=4: strictly above ideal T/2
    assert t_pp > 0.55 * t_seq
    assert t_pp < 0.9 * t_seq


def test_mesh_search_arbitrates_pp():
    """VERDICT acceptance: the factorization search picks pp >= 2 for a
    deep-narrow LM (weight allreduce dominates; pipe shards the weights)
    and rejects pp for a compute-heavy shape (the bubble is pure loss)."""
    from flexflow_tpu.search.machine_model import CHIPS
    from flexflow_tpu.search.mesh_search import search_mesh_shapes

    def winner(batch, L, d, s, heads):
        config = _config((8, 1, 1, 1), batch=batch, argv=["--budget", "2"])
        g = _stack_graph(config, batch, L=L, d=d, s=s, heads=heads)
        shape, _, _, _, results = search_mesh_shapes(
            g, 8, config, axes=("data", "model", "pipe"),
            chip=CHIPS["v5e"])
        return shape, {tuple(sorted(s.items())): c for s, c in results}

    deep, deep_costs = winner(64, 16, 256, 64, 4)
    assert deep["pipe"] >= 2, deep_costs
    heavy, heavy_costs = winner(512, 12, 1024, 512, 16)
    assert heavy["pipe"] == 1, heavy_costs
    assert heavy == {"data": 8, "model": 1, "pipe": 1}


def test_searched_pp_plan_trains():
    """End to end: --search-mesh-shapes on a PIPE_BLOCKS LM re-factorizes
    the mesh onto the pipe axis and the searched plan trains (loss
    decreases) — the searched winner materializes as the working ppermute
    pipeline."""
    import jax

    from flexflow_tpu import FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import (
        TransformerLMConfig,
        build_transformer_lm_pipelined,
    )

    batch = 16
    config = _config((8, 1, 1, 1), batch=batch,
                     argv=["--budget", "2", "--search-mesh-shapes"])
    ff = FFModel(config)
    c = TransformerLMConfig(vocab_size=64, hidden_size=32, num_heads=2,
                            num_layers=4, sequence_length=16,
                            attention_impl="xla")
    build_transformer_lm_pipelined(ff, c, batch_size=batch,
                                   num_microbatches=2)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    sizes = dict(ff.mesh.shape)
    assert sizes["pipe"] >= 2, sizes

    rs = np.random.RandomState(0)
    toks = rs.randint(0, c.vocab_size, (batch, 16)).astype(np.int32)
    pos = np.tile(np.arange(16, dtype=np.int32), (batch, 1))
    labels = rs.randint(0, c.vocab_size, (batch, 16, 1)).astype(np.int32)
    bd = ff._make_batch({"tokens": toks, "positions": pos}, labels)
    step = ff.executor.build_train_step()
    state = (ff._params, ff._state, ff._opt_slots, ff._step, ff._counters)
    losses = []
    for i in range(6):
        out = step(*state, jax.random.key(i), bd)
        state = out[:5]
        losses.append(float(out[-1]))
    assert losses[-1] < losses[0], losses
