"""Resilience subsystem tests (resilience/): atomic async checkpointing,
cross-mesh elastic resume, preemption-safe fit, fault injection.

The headline scenario: a run killed mid-fit (deterministic kill-after-step-K
injection) auto-resumes from the last committed checkpoint onto a *different*
mesh shape (dp=8 → dp=4×tp=2, dp=2×pp=4) and reaches the same final weights/
metrics as an uninterrupted run on the 8-device CPU mesh.
"""

import os
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.quick


DP8 = (8, 1, 1, 1)
DP4_TP2 = (4, 2, 1, 1)
DP2_PP4 = (2, 1, 4, 1)


def _mlp(batch=8, mesh=DP8, seed=0, argv=()):
    sys.argv = ["test", *argv]
    from flexflow_tpu import (
        ActiMode, FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
    )

    config = FFConfig()
    config.mesh_axis_sizes = mesh
    config.batch_size = batch
    config.seed = seed
    ff = FFModel(config)
    x = ff.create_tensor((batch, 16), name="x")
    t = ff.dense(x, 32, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 4, name="fc2")
    t = ff.softmax(t, name="sm")
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])
    return ff


def _data(n=64, d=16, k=4, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, d).astype(np.float32)
    y = rs.randint(0, k, (n, 1)).astype(np.int32)
    return x, y


def _weights(ff):
    import jax

    return {
        "fc1": np.asarray(jax.device_get(ff.get_weight("fc1", "kernel"))),
        "fc2": np.asarray(jax.device_get(ff.get_weight("fc2", "kernel"))),
    }


# ===================================================================
# checkpointer: atomicity + discovery + async semantics
# ===================================================================

def test_atomic_commit_discovery_ignores_tmp_and_torn(tmp_path):
    """Discovery must see only committed checkpoints: in-flight .tmp-* dirs,
    step dirs without a manifest, and torn manifests are all invisible."""
    from flexflow_tpu.resilience import (
        AsyncCheckpointer, latest_checkpoint, list_checkpoints)

    root = str(tmp_path / "ck")
    ck = AsyncCheckpointer(root)
    tree = {"params": {"w": np.arange(4, dtype=np.float32)}}
    ck.save(3, tree, blocking=True)
    good = latest_checkpoint(root)
    assert good and good.endswith("step_00000003")

    # a killed save: tmp dir with full contents but never renamed
    os.makedirs(os.path.join(root, ".tmp-step_00000009-12345"))
    # a torn checkpoint: step dir with half a manifest
    torn = os.path.join(root, "step_00000007")
    os.makedirs(torn)
    with open(os.path.join(torn, "manifest.json"), "w") as f:
        f.write('{"committed": tr')  # truncated mid-write
    # a step dir with no manifest at all
    os.makedirs(os.path.join(root, "step_00000005"))

    assert latest_checkpoint(root) == good
    assert list_checkpoints(root) == [good]


def test_interrupted_async_save_never_corrupts_latest(tmp_path):
    """Acceptance: an async save that dies before its commit point leaves
    the previous latest-good checkpoint untouched and discoverable."""
    from flexflow_tpu.resilience import (
        AsyncCheckpointer, latest_checkpoint, load_checkpoint)

    root = str(tmp_path / "ck")
    ck = AsyncCheckpointer(root)
    v1 = {"params": {"w": np.full(4, 1.0, np.float32)}}
    ck.save(1, v1, blocking=True)
    first = latest_checkpoint(root)

    # kill the writer between serialization and commit
    def die(tmpdir):
        raise KeyboardInterrupt("process killed mid-save")

    ck._pre_commit_hook = die
    ck.save(2, {"params": {"w": np.full(4, 2.0, np.float32)}}, blocking=False)
    with pytest.raises(KeyboardInterrupt):
        ck.wait()

    assert latest_checkpoint(root) == first
    flat, manifest = load_checkpoint(first)
    np.testing.assert_array_equal(flat["['params']['w']"], v1["params"]["w"])
    assert manifest["step"] == 1

    # and the checkpointer recovers: the next save commits normally
    ck._pre_commit_hook = None
    ck.save(3, {"params": {"w": np.full(4, 3.0, np.float32)}}, blocking=True)
    assert latest_checkpoint(root).endswith("step_00000003")


def test_async_save_overlaps_and_prunes(tmp_path):
    """Async saves commit in the background; keep=2 prunes the oldest."""
    from flexflow_tpu.resilience import AsyncCheckpointer, list_checkpoints

    root = str(tmp_path / "ck")
    ck = AsyncCheckpointer(root, keep=2)
    for s in (1, 2, 3):
        ck.save(s, {"w": np.full(8, float(s), np.float32)}, blocking=False)
    ck.wait()
    names = [os.path.basename(p) for p in list_checkpoints(root)]
    assert names == ["step_00000002", "step_00000003"]
    with open(os.path.join(root, "LATEST")) as f:
        assert f.read() == "step_00000003"


def test_bf16_and_int_leaves_roundtrip(tmp_path):
    """npz degrades bfloat16 to raw void bytes; the manifest's recorded
    dtype must reconstruct it exactly (and ints/scalars survive too)."""
    import jax.numpy as jnp

    from flexflow_tpu.resilience import (
        AsyncCheckpointer, latest_checkpoint, load_checkpoint)
    from flexflow_tpu.resilience.checkpointer import snapshot_to_host

    tree = {
        "bf16": jnp.arange(6, dtype=jnp.bfloat16) / 3,
        "i32": jnp.int32(7),
        "f32": jnp.ones((2, 2), jnp.float32) * 0.5,
    }
    root = str(tmp_path / "ck")
    ck = AsyncCheckpointer(root)
    ck.save(0, tree, blocking=True)
    flat, _ = load_checkpoint(latest_checkpoint(root))
    want = snapshot_to_host(tree)
    for k, v in want.items():
        assert flat[k].dtype == v.dtype, k
        np.testing.assert_array_equal(flat[k], v)


def test_abort_discards_inflight_save(tmp_path):
    """abort() models process death: an in-flight async save must never
    commit afterwards; the checkpointer stays usable."""
    from flexflow_tpu.resilience import AsyncCheckpointer, list_checkpoints

    root = str(tmp_path / "ck")
    ck = AsyncCheckpointer(root)
    ck.save(1, {"w": np.zeros(2, np.float32)}, blocking=True)

    # the writer stalls pre-commit until the "kill" lands — deterministic:
    # abort() raises the flag (releasing the hook) before joining
    ck._pre_commit_hook = lambda tmpdir: ck._aborted.wait(5)
    ck.save(2, {"w": np.ones(2, np.float32)}, blocking=False)
    ck.abort()
    names = [os.path.basename(p) for p in list_checkpoints(root)]
    assert names == ["step_00000001"]  # step 2 never committed

    ck._pre_commit_hook = None
    ck.save(3, {"w": np.ones(2, np.float32)}, blocking=True)  # reusable
    assert [os.path.basename(p) for p in list_checkpoints(root)] == [
        "step_00000001", "step_00000003"]


def test_same_step_overwrite_stays_committed(tmp_path):
    """Re-saving an existing step swaps the dirs via atomic renames: the
    new content lands, no .old-* garbage survives, discovery always sees
    exactly one committed checkpoint for the step."""
    from flexflow_tpu.resilience import (
        AsyncCheckpointer, latest_checkpoint, load_checkpoint)

    root = str(tmp_path / "ck")
    ck = AsyncCheckpointer(root)
    ck.save(5, {"w": np.full(2, 1.0, np.float32)}, blocking=True)
    ck.save(5, {"w": np.full(2, 2.0, np.float32)}, blocking=True)
    flat, _ = load_checkpoint(latest_checkpoint(root))
    np.testing.assert_array_equal(flat["['w']"], np.full(2, 2.0, np.float32))
    assert not [n for n in os.listdir(root) if n.startswith(".old-")]


def test_writer_error_surfaces_on_wait(tmp_path, monkeypatch):
    """A failed background write must raise at the next wait(), not vanish
    (silent failed saves would masquerade as durability)."""
    from flexflow_tpu.resilience import AsyncCheckpointer

    ck = AsyncCheckpointer(str(tmp_path / "ck"))

    def boom(tmpdir):
        raise OSError("disk full")

    ck._pre_commit_hook = boom
    ck.save(1, {"w": np.zeros(2, np.float32)}, blocking=False)
    with pytest.raises(OSError, match="disk full"):
        ck.wait()


# ===================================================================
# cross-mesh elastic resume
# ===================================================================

@pytest.mark.parametrize("resume_mesh", [DP4_TP2, DP2_PP4, DP8],
                         ids=["dp4xtp2", "dp2xpp4", "same-dp8"])
def test_cross_mesh_resume_bit_identical(tmp_path, resume_mesh):
    """Save under dp=8, restore under a different factorization of the same
    8 chips: the resumed loss trajectory continues exactly (identical final
    weights and metric counters vs the uninterrupted run)."""
    import jax

    x, y = _data(64)
    root = str(tmp_path / "ck")

    # uninterrupted reference: 2 epochs straight through (deterministic
    # seeded shuffle)
    ref = _mlp(mesh=DP8)
    ref.fit(x, y, epochs=2, batch_size=8, shuffle=True)
    ref_w = _weights(ref)
    ref_counters = jax.device_get(ref._counters)

    # run 1: one epoch under dp=8, checkpoint, stop
    ff1 = _mlp(mesh=DP8)
    ff1.fit(x, y, epochs=1, batch_size=8, shuffle=True)
    mgr1 = ff1.enable_checkpointing(root)
    mgr1.save(int(np.asarray(jax.device_get(ff1._step))),
              cursor={"epoch": 1, "batch": 0}, blocking=True)

    # run 2: fresh process analog — new model, DIFFERENT mesh, auto-resume
    ff2 = _mlp(mesh=resume_mesh,
               argv=["--checkpoint-dir", root, "--auto-resume"])
    from flexflow_tpu.resilience import auto_resume

    extras = auto_resume(ff2, root)
    assert extras is not None and extras["cursor"] == {"epoch": 1, "batch": 0}
    assert extras["mesh_axes"]["data"] == 8  # saved on dp=8
    assert int(np.asarray(jax.device_get(ff2._step))) == 8  # 64/8 steps

    # every restored param carries the NEW mesh's sharding
    w = ff2._params["fc1"]["kernel"]
    assert w.sharding.mesh.shape == ff2.mesh.shape

    # second epoch on the new mesh continues the exact trajectory (fit
    # re-restores via --auto-resume and starts at the saved cursor)
    ff2.fit(x, y, epochs=2, batch_size=8, shuffle=True)
    got_w = _weights(ff2)
    for k in ref_w:
        np.testing.assert_allclose(got_w[k], ref_w[k], rtol=2e-4, atol=1e-6,
                                   err_msg=f"weight {k} diverged")
    got_counters = jax.device_get(ff2._counters)
    for k in ref_counters:
        np.testing.assert_allclose(
            np.asarray(got_counters[k]), np.asarray(ref_counters[k]),
            rtol=2e-4, atol=1e-6, err_msg=f"counter {k} diverged")


def test_resume_epoch_cursor_skips_done_epochs(tmp_path):
    """auto_resume inside fit() starts from the saved (epoch, batch) — the
    already-finished epoch is not re-run (step counter proves it)."""
    import jax

    x, y = _data(32)
    root = str(tmp_path / "ck")

    ff1 = _mlp(mesh=DP8, batch=8)
    ff1.enable_checkpointing(root)
    ff1.fit(x, y, epochs=1, batch_size=8, shuffle=True)
    mgr = ff1._resilience
    mgr.save(int(np.asarray(jax.device_get(ff1._step))),
             cursor={"epoch": 1, "batch": 0}, blocking=True)

    ff2 = _mlp(mesh=DP8, batch=8,
               argv=["--checkpoint-dir", root, "--auto-resume"])
    assert ff2.config.auto_resume and ff2.config.checkpoint_dir == root
    ff2.fit(x, y, epochs=2, batch_size=8, shuffle=True)
    # 32/8 = 4 steps/epoch: epoch 0 restored (4 steps), epoch 1 run (4 more)
    assert int(np.asarray(jax.device_get(ff2._step))) == 8


# ===================================================================
# preemption-safe fit: fault injection + SIGTERM drain
# ===================================================================

@pytest.mark.parametrize("resume_mesh", [DP4_TP2, DP2_PP4],
                         ids=["dp4xtp2", "dp2xpp4"])
def test_kill_after_step_k_auto_resume_cross_mesh(tmp_path, resume_mesh):
    """THE acceptance scenario: mid-fit death at step K (between periodic
    checkpoints) → auto-resume onto a different mesh → final weights match
    the uninterrupted run within fp tolerance."""
    import jax

    from flexflow_tpu.resilience import (
        FaultInjector, SimulatedPreemption, latest_checkpoint)

    x, y = _data(64)
    root = str(tmp_path / "ck")

    ref = _mlp(mesh=DP8)
    ref.fit(x, y, epochs=2, batch_size=8, shuffle=True)  # 16 steps total
    ref_w = _weights(ref)

    # killed run: checkpoint every 2 steps, die after step 5 (NOT on a
    # checkpoint boundary — the last committed state is step 4)
    ff1 = _mlp(mesh=DP8, argv=["--checkpoint-dir", root,
                               "--checkpoint-every", "2"])
    fault = FaultInjector(kill_after_step=5)
    ff1.set_fault_hook(fault)
    with pytest.raises(SimulatedPreemption):
        ff1.fit(x, y, epochs=2, batch_size=8, shuffle=True)
    assert fault.fired
    del ff1  # the process is dead

    last = latest_checkpoint(root)
    assert last is not None and int(last[-8:]) <= 5

    # resumed run: different mesh, --auto-resume, same data/epochs
    ff2 = _mlp(mesh=resume_mesh, argv=["--checkpoint-dir", root,
                                       "--auto-resume"])
    ff2.fit(x, y, epochs=2, batch_size=8, shuffle=True)
    assert int(np.asarray(jax.device_get(ff2._step))) == 16
    got = _weights(ff2)
    for k in ref_w:
        np.testing.assert_allclose(got[k], ref_w[k], rtol=2e-4, atol=1e-6,
                                   err_msg=f"weight {k} diverged after "
                                           f"kill/resume on {resume_mesh}")


def test_sigterm_drains_and_writes_final_snapshot(tmp_path):
    """A preemption notice mid-fit stops the loop after the current step,
    drains the async save, and commits a final snapshot whose cursor
    resumes exactly where training stopped."""
    import jax

    from flexflow_tpu.resilience import latest_checkpoint, load_checkpoint

    x, y = _data(64)
    root = str(tmp_path / "ck")

    ff = _mlp(mesh=DP8, argv=["--checkpoint-dir", root])

    # deliver the "SIGTERM" after step 3 via the fault hook slot (signal
    # delivery itself is covered by test_preemption_handler_signal)
    def notice(step):
        if step == 3:
            _handler_holder[0].request()

    _handler_holder = [None]

    # intercept the handler fit installs
    from flexflow_tpu.resilience import policy as pol

    orig_enter = pol.PreemptionHandler.__enter__

    def capture_enter(self):
        _handler_holder[0] = self
        return orig_enter(self)

    pol.PreemptionHandler.__enter__ = capture_enter
    try:
        ff.set_fault_hook(notice)
        ff.fit(x, y, epochs=2, batch_size=8, shuffle=True)  # returns early
    finally:
        pol.PreemptionHandler.__enter__ = orig_enter

    assert int(np.asarray(jax.device_get(ff._step))) == 4  # stopped at 4
    last = latest_checkpoint(root)
    assert last is not None and last.endswith("step_00000004")
    _, manifest = load_checkpoint(last)
    assert manifest["extras"]["cursor"] == {"epoch": 0, "batch": 4}


def test_preemption_handler_signal():
    """Real SIGTERM delivery sets the flag and the previous handler is
    restored on exit."""
    import signal

    from flexflow_tpu.resilience import PreemptionHandler

    before = signal.getsignal(signal.SIGTERM)
    with PreemptionHandler() as h:
        assert not h.preempted
        os.kill(os.getpid(), signal.SIGTERM)
        assert h.preempted
    assert signal.getsignal(signal.SIGTERM) is before


def test_fault_injector_contract():
    from flexflow_tpu.resilience import FaultInjector, SimulatedPreemption

    with pytest.raises(ValueError):
        FaultInjector(0)
    f = FaultInjector(3)
    f(1)
    f(2)
    with pytest.raises(SimulatedPreemption) as ei:
        f(3)
    assert ei.value.step == 3 and f.fired
    f(4)  # fires only once — the process would already be dead


# ===================================================================
# satellites: dataloader cursor, deprecated wrappers, state-drop bugfix
# ===================================================================

def test_dataloader_resumable_cursor():
    ff = _mlp(batch=4)
    data = np.random.RandomState(0).randn(12, 16).astype(np.float32)
    loader = ff.create_data_loader(ff._input_tensors[0], data)
    loader.next_batch()
    sd = loader.state_dict()
    assert sd == {"next_index": 4}
    b_expected = loader.next_batch()

    loader2 = ff.create_data_loader(ff._input_tensors[0], data)
    loader2.load_state_dict(sd)
    np.testing.assert_array_equal(loader2.next_batch(), b_expected)
    with pytest.raises(ValueError, match="out of range"):
        loader2.load_state_dict({"next_index": 999})


def test_deprecated_checkpoint_api_roundtrips(tmp_path):
    """The old module-level API still works (routed through the resilience
    subsystem) and warns about its deprecation."""
    from flexflow_tpu import checkpoint as ckpt

    ff = _mlp()
    x, y = _data(16)
    ff.fit(x, y, epochs=1, batch_size=8, shuffle=False)
    w = ff.get_weight("fc1", "kernel")
    path = str(tmp_path / "old_api")
    with pytest.warns(DeprecationWarning, match="deprecated"):
        ckpt.save_checkpoint(ff, path)

    ff2 = _mlp(mesh=DP4_TP2)  # even the old API reshards now
    with pytest.warns(DeprecationWarning):
        ckpt.restore_checkpoint(ff2, path)
    np.testing.assert_allclose(ff2.get_weight("fc1", "kernel"), w,
                               rtol=1e-6, atol=0)


def test_restore_rejects_architecture_mismatch(tmp_path):
    """Leaf mismatches raise loudly instead of silently dropping state (the
    old `_state or {}` failure mode). Since fftrans the refusal comes from
    the verify-before-apply transition gate (PlanVerificationError naming
    the leaf and finding class) BEFORE any re-placement; the
    CheckpointCorruptError path stays as the --no-verify-plan backstop."""
    from flexflow_tpu.analysis import PlanVerificationError
    from flexflow_tpu.resilience import CheckpointCorruptError

    ff = _mlp()
    path = str(tmp_path / "ck")
    ff.save_checkpoint(path)

    sys.argv = ["test"]
    from flexflow_tpu import (
        ActiMode, FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
    )

    config = FFConfig()
    config.mesh_axis_sizes = DP8
    config.batch_size = 8
    other = FFModel(config)
    xt = other.create_tensor((8, 16), name="x")
    t = other.dense(xt, 48, ActiMode.AC_MODE_RELU, name="fc1")  # 48 != 32
    t = other.dense(t, 4, name="fc2")
    t = other.softmax(t, name="sm")
    other.compile(optimizer=SGDOptimizer(lr=0.05),
                  loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                  metrics=[MetricsType.METRICS_ACCURACY])
    with pytest.raises((CheckpointCorruptError, PlanVerificationError),
                       match="shape"):
        other.load_checkpoint(path)


def test_resume_through_per_epoch_fit_calls(tmp_path):
    """The keras driver calls fit(epochs=1) once per epoch. A mid-epoch
    checkpoint resumed through that driver must land its batch offset on
    the correct ABSOLUTE epoch (reached only by a later inner fit call)
    and reproduce the uninterrupted run exactly."""
    import jax

    from flexflow_tpu.resilience import (
        FaultInjector, SimulatedPreemption)

    x, y = _data(64)  # 8 batches/epoch
    root = str(tmp_path / "ck")

    ref = _mlp(mesh=DP8)
    for _ in range(3):  # the keras per-epoch pattern
        ref.fit(x, y, epochs=1, batch_size=8, shuffle=True)
    ref_w = _weights(ref)

    # killed run: die mid-epoch-1 (step 13 = epoch 1, batch 5)
    ff1 = _mlp(mesh=DP8, argv=["--checkpoint-dir", root,
                               "--checkpoint-every", "3"])
    ff1.set_fault_hook(FaultInjector(kill_after_step=13))
    with pytest.raises(SimulatedPreemption):
        for _ in range(3):
            ff1.fit(x, y, epochs=1, batch_size=8, shuffle=True)

    # restart, also driven per-epoch: inner fit 1 restores (cursor in
    # absolute epoch 1) and trains nothing or the tail of epoch 0; the
    # later calls pick up the cursor's epoch mid-way
    ff2 = _mlp(mesh=DP4_TP2, argv=["--checkpoint-dir", root,
                                   "--auto-resume"])
    for _ in range(3):
        ff2.fit(x, y, epochs=1, batch_size=8, shuffle=True)
    assert int(np.asarray(jax.device_get(ff2._step))) == 24
    got = _weights(ff2)
    for k in ref_w:
        np.testing.assert_allclose(got[k], ref_w[k], rtol=2e-4, atol=1e-6,
                                   err_msg=f"weight {k} diverged")


def test_auto_resume_fires_at_most_once_per_model(tmp_path):
    """--auto-resume must not rewind live training state on a SECOND fit()
    call in the same process (keras drives one fit per epoch): only the
    first fit restores; later fits continue from live state."""
    import jax

    x, y = _data(32)
    root = str(tmp_path / "ck")

    ff1 = _mlp(mesh=DP8)
    ff1.enable_checkpointing(root)
    ff1.fit(x, y, epochs=1, batch_size=8, shuffle=True)
    ff1._resilience.save(ff1._py_step(), cursor={"epoch": 1, "batch": 0},
                         blocking=True)

    ff2 = _mlp(mesh=DP8, argv=["--checkpoint-dir", root, "--auto-resume"])
    ff2.fit(x, y, epochs=2, batch_size=8, shuffle=True)  # resumes: +4 steps
    assert int(np.asarray(jax.device_get(ff2._step))) == 8
    ff2.fit(x, y, epochs=1, batch_size=8, shuffle=True)  # must NOT rewind
    assert int(np.asarray(jax.device_get(ff2._step))) == 12


def test_discovery_handles_steps_past_eight_digits(tmp_path):
    """%08d grows to 9 digits at step 1e8 — discovery, LATEST, and restore
    ordering must keep working (long-run disk-growth/rewind guard)."""
    from flexflow_tpu.resilience import AsyncCheckpointer, list_checkpoints

    root = str(tmp_path / "ck")
    ck = AsyncCheckpointer(root, keep=2)
    ck.save(99_999_999, {"w": np.zeros(2, np.float32)}, blocking=True)
    ck.save(100_000_000, {"w": np.ones(2, np.float32)}, blocking=True)
    ck.save(100_000_001, {"w": np.ones(2, np.float32)}, blocking=True)
    names = [os.path.basename(p) for p in list_checkpoints(root)]
    assert names == ["step_100000000", "step_100000001"]  # pruned + sorted


def test_repeated_fit_calls_get_fresh_shuffle_orders():
    """The deterministic shuffle must advance across fit() calls: keras
    Model.fit drives one FFModel.fit(epochs=1) per keras epoch, and
    re-training one fixed order every epoch would silently degrade
    convergence. Orders are keyed on the ABSOLUTE epoch count."""
    ff = _mlp()
    o0 = ff._epoch_order(32, 0, True)
    x, y = _data(16)
    ff.fit(x, y, epochs=1, batch_size=8, shuffle=True)  # advances the base
    o1 = ff._epoch_order(32, 0, True)
    assert not np.array_equal(o0, o1)
    # and the absolute indexing is reproducible: a fresh model's epoch 1
    # equals the trained model's post-fit epoch 0
    ff2 = _mlp()
    np.testing.assert_array_equal(o1, ff2._epoch_order(32, 1, True))


def test_barrier_is_noop_single_process():
    from flexflow_tpu.distributed import barrier

    barrier("test")  # must not raise or hang


# ===================================================================
# async overhead (acceptance: within 10% of no-checkpoint baseline) —
# timing-sensitive, excluded from tier-1 via the slow marker; run
# scripts/bench_checkpoint.py for the measured number
# ===================================================================

@pytest.mark.slow
@pytest.mark.full
def test_async_saves_do_not_block_the_caller(tmp_path):
    """The step loop pays only the copy-on-snapshot cost: issuing an async
    save must return well before an equivalent blocking save completes
    (serialize+fsync+commit moved off-thread). Same-process contrast, so
    shared-CI load noise cancels; the quotable fit-level overhead numbers
    (~0.2ms blocking per save, +3.5% wall-clock at --checkpoint-every 32)
    come from scripts/bench_checkpoint.py, whose interleaved wall-clock
    protocol needs a quiet machine."""
    import time

    from flexflow_tpu.resilience import AsyncCheckpointer

    rs = np.random.RandomState(0)
    # a realistically-sized state tree (~64MB): fsync dominates blocking
    tree = {"params": {f"layer{i}": {"kernel": rs.randn(512, 512).astype(
        np.float32)} for i in range(64)}}

    def timed(blocking, root):
        ck = AsyncCheckpointer(root)
        t_issue = []
        t0 = time.perf_counter()
        for s in range(3):
            ti = time.perf_counter()
            ck.save(s, tree, blocking=blocking)
            t_issue.append(time.perf_counter() - ti)
        ck.wait()
        total = time.perf_counter() - t0
        return min(t_issue), total

    t_block, _ = timed(True, str(tmp_path / "b"))
    t_async, total_async = timed(False, str(tmp_path / "a"))
    print(f"issue latency: blocking {t_block*1e3:.1f}ms "
          f"vs async {t_async*1e3:.1f}ms")
    # the async issue path skips serialize+fsync+commit entirely
    assert t_async < t_block, (
        f"async save issue ({t_async:.3f}s) not faster than a full "
        f"blocking save ({t_block:.3f}s)")
    # and the work still happened: all three checkpoints committed
    from flexflow_tpu.resilience import list_checkpoints

    assert len(list_checkpoints(str(tmp_path / "a"))) == 3
