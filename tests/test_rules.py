"""Widened substitution-rule tests: conv/pool/concat/embedding partition
families (substitution.cc:1726-1868), the expressive JSON pattern loader
(substitution_loader.cc analog able to express NEW src→dst rewrites), and
non-DP strategies found on conv nets (AlexNet / Inception)."""

import json
import sys

import numpy as np
import pytest


def _config(mesh_axes, batch=16, argv=()):
    sys.argv = ["test"] + list(argv)
    from flexflow_tpu import FFConfig

    config = FFConfig()
    config.mesh_axis_sizes = mesh_axes
    config.batch_size = batch
    return config


def _pcg_of(ff):
    from tests.test_joint_search import _pcg_of as impl

    return impl(ff)


def _mesh_for(config):
    from flexflow_tpu.machine import build_mesh

    return build_mesh(config.mesh_shape())


def _alexnet_graph(config):
    from flexflow_tpu import FFModel
    from flexflow_tpu.models import build_alexnet

    ff = FFModel(config)
    build_alexnet(ff, batch_size=config.batch_size)
    return ff


@pytest.mark.parametrize("gen_name,op_name", [
    ("partition_conv2d_combine", "OP_CONV2D"),
    ("partition_pool2d_combine", "OP_POOL2D"),
])
def test_conv_family_rewrites_apply(gen_name, op_name):
    """Sample-partition conv/pool rewrites match, apply, and produce a
    consistent parallel state on AlexNet."""
    from flexflow_tpu.fftype import OperatorType as OT
    from flexflow_tpu.search import substitution as S

    config = _config((2, 4, 1, 1))
    ff = _alexnet_graph(config)
    g = _pcg_of(ff)
    xfer = S._GENERATORS[gen_name](2)
    matches = xfer.find_matches(g)
    assert matches, f"{gen_name} found no match on AlexNet"
    ng = xfer.apply(g, matches[0])
    # the rewritten op now has a batch degree of 2
    target = next(n for n in ng.topo_order()
                  if n.op_type == OT[op_name]
                  and any(d.degree > 1 for d in n.outputs[0].shape.dims))
    assert target.outputs[0].shape.dims[0].degree == 2


def test_replicate_conv2d_combine_channel_parallel():
    """Channel-parallel conv rewrite: kernel out-channel sharded, output
    channel dim degree > 1, no partial sums."""
    from flexflow_tpu.fftype import OperatorType as OT
    from flexflow_tpu.search.substitution import (
        create_replicate_conv2d_combine,
    )

    config = _config((2, 4, 1, 1))
    ff = _alexnet_graph(config)
    g = _pcg_of(ff)
    xfer = create_replicate_conv2d_combine(2)
    matches = xfer.find_matches(g)
    assert matches
    ng = xfer.apply(g, matches[0])
    conv = next(n for n in ng.topo_order()
                if n.op_type == OT.OP_CONV2D
                and n._weight_partition.get("kernel") == (0, 2))
    assert conv.outputs[0].shape.dims[1].degree == 2
    assert not any(d.is_replica_dim for d in conv.outputs[0].shape.dims)


def test_partition_embedding_combine():
    from flexflow_tpu import FFModel
    from flexflow_tpu.fftype import DataType, OperatorType as OT
    from flexflow_tpu.search.substitution import (
        create_partition_embedding_combine,
    )

    config = _config((2, 4, 1, 1), batch=8)
    ff = FFModel(config)
    toks = ff.create_tensor((8, 16), DataType.DT_INT32, name="toks")
    h = ff.embedding(toks, 100, 32, name="emb")
    ff.dense(h, 8, name="head")
    g = _pcg_of(ff)
    xfer = create_partition_embedding_combine(2)
    matches = xfer.find_matches(g)
    assert matches
    ng = xfer.apply(g, matches[0])
    emb = next(n for n in ng.topo_order() if n.op_type == OT.OP_EMBEDDING)
    assert emb.outputs[0].shape.dims[0].degree == 2
    # lookup output keeps the table dtype, not the index dtype
    assert emb.outputs[0].shape.dtype == DataType.DT_FLOAT


def test_pattern_rule_loader_novel_rule(tmp_path):
    """The JSON loader ingests a hand-written src→dst pattern no built-in
    generator expresses (a two-op Linear→GELU partition rewrite) and the
    search applies it."""
    rule = {
        "rules": [{
            "name": "partition_linear_gelu_combine",
            "src": [
                {"op": "linear", "inputs": ["$0"], "out": "l1",
                 "constraints": [{"attr": "activation", "eq": "none"},
                                 {"attr": "out_channels", "mod": 2}]},
                {"op": "gelu", "inputs": ["l1"], "out": "g1"},
            ],
            "dst": [
                {"op": "repartition", "inputs": ["$0"],
                 "params": {"dim": 0, "degree": 2}, "out": "r1"},
                {"op": "linear", "inputs": ["r1"], "match": "l1",
                 "out": "l2"},
                {"op": "gelu", "inputs": ["l2"], "match": "g1", "out": "g2"},
                {"op": "combine", "inputs": ["g2"],
                 "params": {"dim": 0, "degree": 2}, "out": "c1"},
            ],
            "map_outputs": [["g1", "c1"]],
        }]
    }
    p = tmp_path / "rules.json"
    p.write_text(json.dumps(rule))

    from flexflow_tpu import ActiMode, FFModel
    from flexflow_tpu.fftype import OperatorType as OT
    from flexflow_tpu.search.substitution import load_rule_collection

    config = _config((2, 4, 1, 1))
    mesh = _mesh_for(config)
    xfers = load_rule_collection(str(p), mesh)
    assert len(xfers) == 1 and xfers[0].name == "partition_linear_gelu_combine"

    ff = FFModel(config)
    x = ff.create_tensor((16, 32))
    t = ff.dense(x, 64, name="fc1")
    t = ff.gelu(t, name="act")
    ff.dense(t, 8, name="head")
    g = _pcg_of(ff)
    matches = xfers[0].find_matches(g)
    assert matches, "novel pattern rule found no match"
    ng = xfers[0].apply(g, matches[0])
    types = [n.op_type for n in ng.topo_order()]
    assert OT.OP_REPARTITION in types and OT.OP_COMBINE in types
    lin = next(n for n in ng.topo_order()
               if n.op_type == OT.OP_LINEAR and n.name == "fc1")
    assert lin.outputs[0].shape.dims[0].degree == 2


def test_pattern_rule_loader_rejects_malformed(tmp_path):
    from flexflow_tpu.search.substitution import load_rule_collection

    config = _config((2, 4, 1, 1))
    mesh = _mesh_for(config)
    bad = {"rules": [{"name": "x", "src": [{"op": "nosuchop"}],
                      "dst": [], "map_outputs": []}]}
    p = tmp_path / "bad.json"
    p.write_text(json.dumps(bad))
    with pytest.raises(ValueError, match="unknown op type"):
        load_rule_collection(str(p), mesh)


@pytest.mark.parametrize("model_name", ["alexnet", "inception"])
def test_conv_net_search_finds_non_dp(model_name):
    """The joint search on AlexNet / Inception must find a strategy using
    the model axis (channel-parallel conv or TP dense), not plain DP."""
    from flexflow_tpu import FFModel
    from flexflow_tpu.models import build_alexnet, build_inception_v3
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.joint import joint_graph_optimize
    from flexflow_tpu.search.machine_model import machine_model_for_mesh

    config = _config((1, 4, 1, 1), batch=8,
                     argv=["--budget", "6", "--enable-attribute-parallel",
                           "--enable-parameter-parallel"])
    ff = FFModel(config)
    if model_name == "alexnet":
        build_alexnet(ff, batch_size=8)
    else:
        build_inception_v3(ff, batch_size=8)
    g = _pcg_of(ff)
    mesh = _mesh_for(config)
    cm = CostModel(machine_model_for_mesh(mesh))
    best_g, choice, us = joint_graph_optimize(g, mesh, config, cm)
    used = {cfg.name for cfg in choice.values() if cfg is not None}
    rewritten = any(
        d.degree > 1 for n in best_g.topo_order()
        for pt in n.outputs for d in pt.shape.dims)
    assert rewritten or (used - {"dp"}), (
        f"search found only DP on {model_name}: {used}")


def test_alexnet_trains_through_search():
    """End-to-end: AlexNet compiled through the joint search (conv rewrites
    + conv TP configs live) still trains a step without error."""
    from flexflow_tpu import FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import build_alexnet

    config = _config((2, 2, 1, 1), batch=8,
                     argv=["--budget", "4", "--enable-attribute-parallel"])
    ff = FFModel(config)
    build_alexnet(ff, batch_size=8)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    rs = np.random.RandomState(0)
    xs = rs.randn(16, 3, 224, 224).astype(np.float32)
    ys = rs.randint(0, 10, (16, 1)).astype(np.int32)
    ff.fit(xs, ys, epochs=1)
    assert ff.get_perf_metrics().train_all == 16
