"""ffscope observability plane: op-grain profiling, flight recorder,
hang watchdog (scope/, docs/observability.md).

Acceptance surface:

  - the xplane wire decoder parses a hand-encoded XSpace (no TF
    dependency) and attribution maps instruction durations back to PCG
    node names through named-scope paths, fwd/bwd split included;
  - a --profile-every fit produces a report `profile` section with a
    measured column for every report op, the attribution identity
    re-verifies from the JSON alone, and run_doctor --check enforces it;
  - the flight recorder's ring bound holds, steady-state records
    allocate no new slot objects (slot identity pinned), and a
    HealthAbort fit leaves a well-formed flight.json behind;
  - an injected stall fires the watchdog, which names the lagging host
    from the file heartbeat channel and dumps a parseable flight.json;
  - an injected single-op slowdown yields an op-grain drift advisory
    and recalibration re-measures ONLY that op (0 re-measures for
    undrifted ops — pinned by monkeypatched calibrate call counts);
  - the fflint `unnamed_op_scope` rule flags bare op dispatch in
    executor.py/ops/, honors named_scope wrapping + pragmas, and the
    real executor sweeps clean.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

from flexflow_tpu import telemetry
from flexflow_tpu.scope import flightrec
from flexflow_tpu.scope.attribution import (
    attribute_trace,
    build_profile_section,
    verify_profile_section,
)
from flexflow_tpu.scope.flightrec import FlightRecorder
from flexflow_tpu.scope.watchdog import HangWatchdog, THREAD_NAME
from flexflow_tpu.telemetry.recorder import read_jsonl

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_session_leak():
    yield
    telemetry.deactivate()
    # tests toggle the global flight recorder; restore the default
    flightrec.configure(capacity=flightrec.DEFAULT_CAPACITY, enabled=True)


# ------------------------------------------------------------- wire format

def _vi(v: int) -> bytes:
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _varint_field(fnum: int, v: int) -> bytes:
    return _vi((fnum << 3) | 0) + _vi(v)


def _ld(fnum: int, payload: bytes) -> bytes:
    return _vi((fnum << 3) | 2) + _vi(len(payload)) + payload


def _hlo_proto(instr_scopes: dict) -> bytes:
    """{instruction_name: named_scope_path} → serialized HloProto."""
    instrs = b""
    for name, scope in instr_scopes.items():
        op_meta = _ld(2, scope.encode())           # OpMetadata.op_name
        instrs += _ld(2, _ld(1, name.encode())     # HloInstructionProto
                      + _ld(7, op_meta))
    comp = _ld(3, instrs)                          # HloComputationProto
    return _ld(1, comp)                            # HloModuleProto


def _xspace(instr_scopes: dict, durations_ps: dict,
            program_id: int = 5) -> bytes:
    """One metadata plane (Hlo Proto stat) + one /host:CPU plane whose
    line carries an event per instruction with the given duration."""
    # metadata plane: stat_metadata {1: "Hlo Proto"}, one XEventMetadata
    # named "jit_f(<pid>)" whose stat ref=1 holds the HloProto bytes
    hlo_stat = _varint_field(7, 1) + _ld(6, _hlo_proto(instr_scopes))
    emd = (_varint_field(1, 7) + _ld(2, b"jit_f(%d)" % program_id)
           + _ld(5, hlo_stat))
    meta_plane = (_ld(2, b"/host:metadata")
                  + _ld(4, _varint_field(1, 7) + _ld(2, emd))
                  + _ld(5, _varint_field(1, 1)
                        + _ld(2, _varint_field(1, 1)
                              + _ld(2, b"Hlo Proto"))))
    # device plane: stat_metadata {1: "program_id"}; event_metadata id i
    # → instruction name; one line with one event per instruction
    dev = _ld(2, b"/host:CPU")
    events = b""
    for i, (name, dur) in enumerate(durations_ps.items(), start=10):
        dev += _ld(4, _varint_field(1, i)
                   + _ld(2, _varint_field(1, i) + _ld(2, name.encode())))
        pid_stat = _varint_field(7, 1) + _varint_field(3, program_id)
        events += _ld(4, _varint_field(1, i) + _varint_field(3, dur)
                      + _ld(4, pid_stat))
    dev += _ld(3, _varint_field(1, 0) + events)    # XLine id 0
    dev += _ld(5, _varint_field(1, 1)
               + _ld(2, _varint_field(1, 1) + _ld(2, b"program_id")))
    return _ld(1, meta_plane) + _ld(1, dev)


@pytest.mark.quick
def test_xplane_decode_and_attribution_synthetic(tmp_path):
    """Hand-encoded XSpace bytes → per-op seconds: forward and backward
    (transpose-wrapped) paths attribute to the op, runtime scopes land
    in extras, unknown scopes in unattributed_s — and the built section
    passes its own identity check."""
    scopes = {
        "dot.1": "jit(f)/jit(main)/jvp(dense1)/dot_general",
        "dot.2": "jit(f)/jit(main)/transpose(jvp(dense1))/dot_general",
        "add.3": "jit(f)/jit(main)/weight_update/add",
        "mul.4": "jit(f)/jit(main)/somewhere_else/mul",
    }
    durs = {"dot.1": 2_000_000_000, "dot.2": 1_000_000_000,
            "add.3": 500_000_000, "mul.4": 300_000_000}
    d = tmp_path / "trace"
    d.mkdir()
    (d / "host.xplane.pb").write_bytes(_xspace(scopes, durs))

    attr = attribute_trace(str(d), ["dense1", "dense2"])
    op = attr["ops"]["dense1"]
    assert op["fwd_s"] == pytest.approx(2e-3)
    assert op["bwd_s"] == pytest.approx(1e-3)
    assert op["measured_s"] == pytest.approx(3e-3)
    assert op["events"] == 2
    assert attr["extras"]["weight_update"] == pytest.approx(0.5e-3)
    assert attr["unattributed_s"] == pytest.approx(0.3e-3)
    assert attr["attributed_s"] == pytest.approx(3.5e-3)
    assert attr["parallelism"] == 1

    section = build_profile_section(
        attr, step=7, device_time_s=4e-3, source="xplane",
        all_op_names=["dense1", "dense2"])
    # every requested op has a row, absent ones with measured 0
    rows = {r["name"]: r for r in section["ops"]}
    assert rows["dense2"]["measured_s"] == 0.0
    assert verify_profile_section(section) == []
    # break the identity: inflate device budget violation
    bad = dict(section, device_time_s=1e-6, parallelism=1)
    assert any("exceeds device budget" in p
               for p in verify_profile_section(bad))


@pytest.mark.quick
def test_truncated_xplane_is_tolerated(tmp_path):
    d = tmp_path / "trace"
    d.mkdir()
    buf = _xspace({"dot.1": "jit(f)/dense1/dot"}, {"dot.1": 10})
    (d / "torn.xplane.pb").write_bytes(buf[: len(buf) // 2])
    attr = attribute_trace(str(d), ["dense1"])  # no raise
    assert attr["attributed_s"] >= 0.0


# --------------------------------------------------------- flight recorder

@pytest.mark.quick
def test_flight_ring_bound_and_order():
    rec = FlightRecorder(capacity=16)
    for i in range(3 * 16 + 5):
        rec.record("span", "op%d" % i, i)
    snap = rec.snapshot()
    assert len(snap) == 16                      # ring bound holds
    seqs = [e["seq"] for e in snap]
    assert seqs == sorted(seqs)                 # oldest-first
    assert seqs[-1] == 3 * 16 + 5               # newest retained
    assert snap[-1]["name"] == "op%d" % (3 * 16 + 4)


@pytest.mark.quick
def test_flight_zero_alloc_steady_state():
    """Overhead guard: a steady-state record is index assignment into
    preallocated slots — the slot objects (and the ring list) keep their
    identity across thousands of records."""
    rec = FlightRecorder(capacity=32)
    ring_id = id(rec._ring)
    slot_ids = [id(s) for s in rec._ring]
    for i in range(10 * 32):
        rec.record("span", "step", None)
    assert id(rec._ring) == ring_id
    assert [id(s) for s in rec._ring] == slot_ids


@pytest.mark.quick
def test_flight_dump_well_formed(tmp_path):
    rec = FlightRecorder(capacity=8)
    rec.note_step(3)
    rec.record("instant", "alert.nan_loss", None)
    rec.record("span", "obj", object())         # non-scalar → repr'd
    path = rec.dump(str(tmp_path), "unit_test", extra={"k": 1})
    doc = json.load(open(path))
    assert doc["kind"] == "flight_record"
    assert doc["reason"] == "unit_test"
    assert doc["capacity"] == 8 and doc["last_step"] == 3
    assert doc["k"] == 1
    assert len(doc["events"]) <= doc["capacity"]
    assert all("seq" in e and "kind" in e and "name" in e
               for e in doc["events"])
    json.dumps(doc)  # fully serializable (repr'd values included)


@pytest.mark.quick
def test_flight_module_plane_and_disable(tmp_path, monkeypatch):
    # telemetry dispatchers feed the global recorder even with NO
    # session active (the always-on contract)
    flightrec.configure(capacity=64, enabled=True)
    rec = flightrec.get_recorder()
    before = rec._seq
    telemetry.instant("x.y")
    with telemetry.span("a.b"):
        pass
    assert rec._seq >= before + 2
    # no directory resolvable → dump is skipped, never litters CWD
    monkeypatch.delenv("FF_FLIGHT_DIR", raising=False)
    assert flightrec.dump("nowhere") is None
    monkeypatch.setenv("FF_FLIGHT_DIR", str(tmp_path))
    assert flightrec.dump("env_dir") == str(tmp_path / "flight.json")
    # disabled: every hook is a no-op and dump returns None
    flightrec.configure(enabled=False)
    telemetry.instant("dropped")
    assert flightrec.get_recorder() is None
    assert flightrec.dump("disabled") is None


# ---------------------------------------------------------------- watchdog

@pytest.mark.quick
def test_watchdog_lagging_host_from_heartbeats():
    hbs = [{"host": 0, "step": 7, "time_unix": 100.0},
           {"host": 1, "step": 3, "time_unix": 120.0},
           {"host": 2, "step": 7, "time_unix": 90.0}]
    assert HangWatchdog.lagging_host(hbs) == 1    # lowest step wins
    hbs[1]["step"] = 7
    assert HangWatchdog.lagging_host(hbs) == 2    # then oldest beat
    assert HangWatchdog.lagging_host([]) is None


def test_watchdog_fires_on_stall_and_names_host(tmp_path):
    """No beat within the deadline → one firing: flight.json dumped with
    a watchdog section naming the lagging host (read from the file
    heartbeat channel, which includes another host's stale file)."""
    fired = []
    wd = HangWatchdog(timeout_s=0.3, multiplier=10.0,
                      directory=str(tmp_path), host_index=1,
                      on_fire=fired.append, poll_interval_s=0.05)
    # another host stopped beating at an older step
    hb_dir = tmp_path / "heartbeats"
    hb_dir.mkdir()
    (hb_dir / "host-0.json").write_text(
        json.dumps({"host": 0, "step": 1, "time_unix": time.time()}))
    wd.start()
    try:
        wd.beat(4)
        wd.beat(5)
        deadline = time.time() + 5.0
        while not fired and time.time() < deadline:
            time.sleep(0.05)
    finally:
        wd.stop()
    assert wd.fired == 1 and fired
    info = fired[0]
    assert info["stalled_s"] > 0.3
    assert info["last_step"] == 5
    assert info["lagging_host"] == 0
    assert {h["host"] for h in info["hosts"]} == {0, 1}
    doc = json.load(open(tmp_path / "flight.json"))
    assert doc["reason"] == "watchdog"
    assert doc["watchdog"]["lagging_host"] == 0


@pytest.mark.quick
def test_watchdog_rearms_only_after_beat(tmp_path):
    # multiplier=0: the fixed timeout governs even after the long first
    # stall inflates the inter-beat EMA
    wd = HangWatchdog(timeout_s=0.15, multiplier=0.0,
                      directory=str(tmp_path), poll_interval_s=0.03)
    wd.start()
    try:
        wd.beat(1)
        time.sleep(0.6)
        assert wd.fired == 1                     # fires ONCE per stall
        wd.beat(2)                               # re-arms
        time.sleep(0.5)
        assert wd.fired == 2
    finally:
        wd.stop()
    assert wd._thread is None
    import threading

    assert all(t.name != THREAD_NAME for t in threading.enumerate())


# ------------------------------------------------------------ model e2e

def _compiled_model(extra_argv=()):
    sys.argv = ["test"] + list(extra_argv)
    from flexflow_tpu import (
        ActiMode, FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
    )

    config = FFConfig()
    ff = FFModel(config)
    x = ff.create_tensor((32, 64))
    t = ff.dense(x, 128, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 16)
    t = ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])
    return ff


def _train_data(n=128, in_dim=64, classes=16):
    rs = np.random.RandomState(0)
    return (rs.randn(n, in_dim).astype(np.float32),
            rs.randint(0, classes, (n, 1)).astype(np.int32))


def _run_doctor(argv):
    """Invoke scripts/run_doctor.py main() in-process (SystemExit on a
    failed --check)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "run_doctor_under_test",
        os.path.join(REPO, "scripts", "run_doctor.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    old = sys.argv
    sys.argv = ["run_doctor"] + list(argv)
    try:
        mod.main()
    finally:
        sys.argv = old


def test_profile_every_fit_attribution_and_doctor_gate(tmp_path):
    """--profile-every: the report gains a `profile` section with a
    measured column for every report op, the identity re-verifies from
    the JSON alone (run_doctor --check), and tampering trips the gate."""
    tdir = tmp_path / "tel"
    ff = _compiled_model(["--telemetry-dir", str(tdir), "--diagnostics",
                          "--profile-every", "2"])
    x, y = _train_data()
    ff.fit(x, y, epochs=1, batch_size=32, verbose=False)
    telemetry.deactivate()

    rep = json.load(open(tdir / "strategy_report.json"))
    prof = rep["profile"]
    assert prof["source"] == "xplane"
    report_ops = {o["name"] for o in rep["ops"]}
    rows = {r["name"]: r for r in prof["ops"]}
    assert report_ops <= set(rows)               # a row for EVERY op
    assert sum(r["measured_s"] for r in prof["ops"]) > 0
    measured = [r for r in prof["ops"] if r["measured_s"] > 0]
    assert all("fidelity" in r for r in measured if r.get("predicted_s"))
    assert verify_profile_section(prof) == []
    # markdown twin renders the measured table
    md = (tdir / "strategy_report.md").read_text()
    assert "Measured profile (ffscope)" in md
    # ffpulse: op_time_s histograms landed in a metrics snapshot
    recs = read_jsonl(tdir / "metrics.jsonl")
    assert any(r.get("kind") == "profile" for r in recs)
    snaps = [r for r in recs if r.get("kind") == "metrics_snapshot"]
    assert any(
        any(k.startswith("op_time_s") for k in
            (s.get("metrics", {}).get("histograms") or {}))
        for s in snaps)
    # doctor renders one measured-vs-predicted table
    from flexflow_tpu.diagnostics.doctor import diagnose, render

    d = diagnose(str(tdir))
    assert d["profile"] is not None
    assert "Op profile (ffscope)" in render(d)
    _run_doctor([str(tdir), "--check", "--out", str(tmp_path / "r.md")])
    # tamper: a fidelity that no longer reproduces must trip the gate
    for r in rep["profile"]["ops"]:
        if r.get("fidelity"):
            r["fidelity"] *= 3.0
            break
    json.dump(rep, open(tdir / "strategy_report.json", "w"))
    with pytest.raises(SystemExit):
        _run_doctor([str(tdir), "--check"])


def test_health_abort_leaves_flight_record(tmp_path):
    """Crash dump: a HealthAbort fit leaves a parseable flight.json
    (reason=HealthAbort, ring sized by --flight-events) that
    run_doctor --check validates — and rejects once malformed."""
    tdir = tmp_path / "tel"
    ff = _compiled_model(["--telemetry-dir", str(tdir), "--diagnostics",
                          "--health-abort-on", "nan_loss",
                          "--flight-events", "64"])
    x, y = _train_data()
    x[40, 3] = np.nan
    from flexflow_tpu.diagnostics import HealthAbort

    with pytest.raises(HealthAbort):
        ff.fit(x, y, epochs=1, batch_size=32, verbose=False)
    telemetry.deactivate()

    doc = json.load(open(tdir / "flight.json"))
    assert doc["kind"] == "flight_record"
    assert doc["reason"] == "HealthAbort"
    assert doc["capacity"] == 64
    assert 0 < len(doc["events"]) <= 64
    # the ring saw real telemetry traffic, ending near the abort
    kinds = {e["kind"] for e in doc["events"]}
    assert "step" in kinds or "span" in kinds
    from flexflow_tpu.diagnostics.doctor import diagnose, render

    d = diagnose(str(tdir))
    assert d["flight"]["reason"] == "HealthAbort"
    assert "Flight record (ffscope)" in render(d)
    _run_doctor([str(tdir), "--check"])
    doc["events"] = doc["events"] * 40            # breaks the ring bound
    json.dump(doc, open(tdir / "flight.json", "w"))
    with pytest.raises(SystemExit):
        _run_doctor([str(tdir), "--check"])


def test_injected_stall_fires_watchdog_in_fit(tmp_path):
    """--watchdog-timeout + a fault hook that sleeps past the deadline:
    the watchdog fires mid-fit, dumps flight.json with a watchdog
    section naming the (single) host, and records a hang_watchdog
    alert; the fit then completes normally."""
    tdir = tmp_path / "tel"
    ff = _compiled_model(["--telemetry-dir", str(tdir), "--diagnostics",
                          "--watchdog-timeout", "0.6"])

    def stall(step):
        if step == 2:
            time.sleep(1.8)

    ff.set_fault_hook(stall)
    x, y = _train_data()
    ff.fit(x, y, epochs=1, batch_size=32, verbose=False)
    telemetry.deactivate()

    doc = json.load(open(tdir / "flight.json"))
    assert doc["reason"] == "watchdog"
    wd = doc["watchdog"]
    assert wd["stalled_s"] > 0.6
    assert wd["host"] == 0 and wd["lagging_host"] == 0
    assert (tdir / "heartbeats" / "host-0.json").exists()
    alerts = read_jsonl(tdir / "alerts.jsonl")
    hang = [a for a in alerts if a.get("rule") == "hang_watchdog"]
    assert hang and hang[0]["level"] == "error"
    from flexflow_tpu.diagnostics.doctor import diagnose, render

    d = diagnose(str(tdir))
    assert d["watchdog"] is not None
    assert "Hang watchdog (ffscope)" in render(d)


# -------------------------------------------- targeted recalibration

def test_op_drift_targeted_recalibration_refreshes_only_drifted_op(
        tmp_path):
    """Acceptance: an injected single-op slowdown yields an op-grain
    advisory and recalibration re-measures ONLY that op — 0 re-measures
    for undrifted ops, pinned by counting CostModel.calibrate calls."""
    tdir = tmp_path / "tel"
    ff = _compiled_model([
        "--telemetry-dir", str(tdir), "--diagnostics", "--budget", "8",
        "--enable-parameter-parallel", "--mesh", "4,2,1,1"])
    diag = ff.get_diagnostics()
    rep = diag.report
    assert rep["mode"] == "searched" and diag.drift is not None

    priced = [o for o in rep["ops"]
              if o["compute_s"] + o["comm_s"] > 0]
    assert len(priced) >= 3
    slow_op = priced[1]["name"]
    # synthesize a profiled step: every op at fidelity 2.0 except the
    # injected one at 200x — only IT deviates from the step median
    rows = []
    for o in priced:
        pred = o["compute_s"] + o["comm_s"]
        scale = 200.0 if o["name"] == slow_op else 2.0
        rows.append({"name": o["name"], "measured_s": pred * scale,
                     "fwd_s": pred * scale, "bwd_s": 0.0, "events": 4})
    section = {
        "source": "xplane", "step": 9, "device_time_s": 1.0,
        "devices": 1, "parallelism": 8, "slop": 0.25,
        "attributed_s": sum(r["measured_s"] for r in rows),
        "unattributed_s": 0.0, "ops": rows, "extras": {},
    }
    diag.on_profile(section)
    assert diag.drift.pending_op_refresh == {slow_op}
    assert [a.op for a in diag.drift.op_advisories] == [slow_op]
    alerts = read_jsonl(tdir / "alerts.jsonl")
    op_advs = [a for a in alerts if a.get("rule") == "costmodel_op_drift"]
    assert [a["op"] for a in op_advs] == [slow_op]
    # report persisted with the annotated profile section
    rep2 = json.load(open(tdir / "strategy_report.json"))
    assert rep2["profile"]["step"] == 9

    from flexflow_tpu.diagnostics.drift import recalibrate_model

    us, _choice = ff._search_result
    measured = []
    us.cm.calibrate = (lambda node, fn, args, **kw:
                       (measured.append(node.name), (1e-4, 2e-4))[1])
    t = recalibrate_model(ff)
    assert t is not None
    assert measured == [slow_op]                 # ONLY the drifted op
    assert diag.drift.pending_op_refresh == set()
    assert us.cm.calib_stats["targeted"] == [slow_op]
    telemetry.deactivate()


@pytest.mark.quick
def test_standalone_profile_source_emits_no_op_drift():
    """profiling.py's standalone kernels flow into the same schema but
    must NOT trigger op-grain drift advisories (unfused timings say
    nothing about in-situ pricing)."""
    from flexflow_tpu.diagnostics.drift import DriftMonitor
    from flexflow_tpu.profiling import profile_section_from_rows

    rows = [("dense1", "OP_LINEAR", 1e-3, 2e-3),
            ("dense2", "OP_LINEAR", 5e-4, 1e-3)]
    section = profile_section_from_rows(rows)
    assert section["source"] == "standalone"
    assert {r["name"] for r in section["ops"]} == {"dense1", "dense2"}
    assert verify_profile_section(section) == []
    m = DriftMonitor(predicted_s=0.1)
    # the manager gates note_profile on source == "xplane"; mimic it
    if section.get("source") == "xplane":
        m.note_profile(section)
    assert m.op_advisories == [] and m.pending_op_refresh == set()


# ------------------------------------------------------------- serving

def test_serving_profile_step_and_xprof_dir(tmp_path):
    """Satellite: the serving engine's step loop profiles under the same
    plane — profile_step returns a `source: serving` section, and
    --xprof-dir wraps run_until_drained in a jax.profiler trace that
    leaves a dump behind."""
    xdir = tmp_path / "xprof"
    sys.argv = ["test", "--xprof-dir", str(xdir)]
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import TransformerLMConfig, build_transformer_lm

    cfg = FFConfig()
    if cfg.mesh_axis_sizes is None:
        cfg.mesh_axis_sizes = (1, 1, 1, 1)
    cfg.batch_size = 1
    ff = FFModel(cfg)
    build_transformer_lm(
        ff, TransformerLMConfig(vocab_size=64, hidden_size=32,
                                num_heads=4, num_layers=2,
                                sequence_length=32, attention_impl="xla"),
        batch_size=1)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    eng = ff.serve(slots=2, max_new_tokens=4, prefill_chunk=4)
    eng.submit([3, 7, 11, 2])
    eng.step()                                   # prefill underway
    section = eng.profile_step()
    assert section is not None
    assert section["source"] == "serving"
    assert section["ops"]                        # a row per graph op
    assert verify_profile_section(section) == []
    assert eng.last_profile is section
    eng.run_until_drained()
    assert xdir.exists() and any(os.scandir(xdir))  # xprof dump written


# ----------------------------------------------------------------- lint

def _lint(src, path="flexflow_tpu/executor.py"):
    from flexflow_tpu.analysis.lint import lint_source

    return [f for f in lint_source(src, path=path,
                                   select=("unnamed_op_scope",))]


@pytest.mark.quick
def test_lint_unnamed_op_scope_matrix():
    bare = (
        "def fwd(node, ins):\n"
        "    return node.op_def.forward(node.params, ins, {}, None, ctx)\n")
    assert [f.code for f in _lint(bare)] == ["unnamed_op_scope"]
    # wrapped in named_scope → clean
    scoped = (
        "def fwd(node, ins):\n"
        "    with jax.named_scope(node.name):\n"
        "        return node.op_def.forward(node.params, ins, {}, None,\n"
        "                                   ctx)\n")
    assert _lint(scoped) == []
    # pragma'd (runtime nesting under a caller's scope) → clean
    pragma = (
        "def fwd(node, ins):\n"
        "    return node.op_def.forward(  # fflint: ok unnamed_op_scope\n"
        "        node.params, ins, {}, None, ctx)\n")
    assert _lint(pragma) == []
    # the scope must wrap THIS dispatch, not live past a def boundary
    nested = (
        "def outer(node, ins):\n"
        "    with jax.named_scope(node.name):\n"
        "        def run(t):\n"
        "            return node.op_def.forward(node.params, t, {},\n"
        "                                       None, ctx)\n"
        "        return run(ins)\n")
    assert [f.code for f in _lint(nested)] == ["unnamed_op_scope"]
    # path gate: the calibration harness times ops standalone — exempt
    assert _lint(bare, path="flexflow_tpu/search/cost_model.py") == []
    assert [f.code for f in _lint(bare, path="flexflow_tpu/ops/core.py")
            ] == ["unnamed_op_scope"]


@pytest.mark.quick
def test_lint_repo_sweep_clean():
    """Every real op dispatch is scoped or carries a justified pragma."""
    from flexflow_tpu.analysis.lint import lint_paths

    findings = lint_paths(
        [os.path.join(REPO, "flexflow_tpu")],
        select=("unnamed_op_scope",))
    assert findings == []


# ---------------------------------------------------------------- config

@pytest.mark.quick
def test_config_flags_parse():
    sys.argv = ["test", "--profile-every", "3", "--watchdog-timeout",
                "5.5", "--watchdog-multiplier", "12", "--watchdog-abort",
                "--flight-events", "128"]
    from flexflow_tpu import FFConfig

    cfg = FFConfig()
    assert cfg.profile_every == 3
    assert cfg.watchdog_timeout == 5.5
    assert cfg.watchdog_multiplier == 12.0
    assert cfg.watchdog_abort is True
    assert cfg.flight_events == 128
