"""Unity search tests: machine model, reshard classification, the DP +
refinement, and end-to-end search → strategy → training equivalence."""

import sys

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P


def _machine(axis_sizes):
    from flexflow_tpu.search.machine_model import CHIPS, TPUMachineModel

    return TPUMachineModel(CHIPS["v5p"], dict(axis_sizes))


def test_collective_costs_ordering():
    m = _machine({"data": 4, "model": 2})
    B = 64 * 1024 * 1024  # per-chip shard bytes
    ag = m.all_gather(B * 4, "data")  # gathered output = n * shard
    ar = m.all_reduce(B, "data")
    a2a = m.all_to_all(B, "data")
    assert 0 < a2a < ag  # all_to_all moves only (n-1)/n of one shard
    assert ar > 0
    assert m.all_gather(B, "absent_axis") == 0.0
    # latency grows with axis size
    assert m.all_reduce(1, "data") > m.all_reduce(1, "model")


def test_classify_reshard():
    from flexflow_tpu.fftype import DataType
    from flexflow_tpu.search.cost_model import classify_reshard

    m = _machine({"data": 4, "model": 4})
    shape = (64, 1024)
    dp = ((("data",),) + ((),))
    dp_feat = (("data",), ("model",))
    # same spec: free
    assert classify_reshard(shape, dp, dp, DataType.DT_FLOAT, m) == 0.0
    # adding an axis (slicing) is free
    assert classify_reshard(shape, dp, dp_feat, DataType.DT_FLOAT, m) == 0.0
    # removing an axis costs an all_gather
    c = classify_reshard(shape, dp_feat, dp, DataType.DT_FLOAT, m)
    assert c > 0
    # moving an axis between dims costs an all_to_all (cheaper than gather)
    moved = ((), ("data",))
    c2 = classify_reshard(shape, dp, moved, DataType.DT_FLOAT, m)
    assert 0 < c2 < m.all_gather(64 * 1024 * 4, "data") + 1


def _build_big_mlp(mesh_axes, hidden, strategy=None, argv=()):
    sys.argv = ["test"] + list(argv)
    from flexflow_tpu import ActiMode, FFConfig, FFModel, LossType, SGDOptimizer

    config = FFConfig()
    config.mesh_axis_sizes = mesh_axes
    config.batch_size = 16
    ff = FFModel(config)
    x = ff.create_tensor((16, 64))
    t = ff.dense(x, hidden, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, hidden, ActiMode.AC_MODE_RELU, name="fc2")
    t = ff.dense(t, 8, name="head")
    t = ff.softmax(t, name="sm")
    if strategy is not None:
        ff.set_strategy(strategy)
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff


def test_search_finds_tp_for_weight_heavy_mlp():
    """Tiny batch + huge weights: DP's per-step weight allreduce dwarfs TP's
    activation collectives, so the search must shard the big pair."""
    from flexflow_tpu.search import CostModel, UnitySearch, machine_model_for_mesh

    ff = _build_big_mlp((2, 4, 1, 1), hidden=4096,
                        argv=["--enable-parameter-parallel"])
    # compile already ran the search via the flag; check what it chose
    fc1 = next(n for n in ff.graph.topo_order() if n.name == "fc1")
    spec = fc1.weight_axes.get("kernel")
    assert spec is not None and "model" in str(spec), (
        f"search kept fc1 replicated: {ff._strategy}"
    )


def test_search_never_worse_than_dp():
    """The chosen strategy's modeled cost must never exceed pure DP's (the
    search starts from DP and only keeps improving moves)."""
    sys.argv = ["test", "--budget", "4"]
    from flexflow_tpu import ActiMode, FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.search import CostModel, UnitySearch, machine_model_for_mesh

    config = FFConfig()
    config.mesh_axis_sizes = (4, 2, 1, 1)
    config.batch_size = 256
    ff = FFModel(config)
    x = ff.create_tensor((256, 64))
    t = ff.dense(x, 512, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 8, name="head")
    t = ff.softmax(t, name="sm")
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    mm = machine_model_for_mesh(ff.mesh)
    s = UnitySearch(ff.graph, ff.mesh, config, CostModel(mm))
    chosen = s.run()
    dp_choice = {n.guid: s.node_configs(n)[0] for n in s.order}
    chosen_cost, _ = s.evaluate(chosen)
    dp_cost, _ = s.evaluate(dp_choice)
    assert chosen_cost <= dp_cost * 1.0001


def test_searched_strategy_trains_equivalently():
    """The searched strategy must produce the same training result as the
    unsharded baseline (numerics invariance of the parallelization)."""
    rs = np.random.RandomState(0)
    x = rs.randn(32, 64).astype(np.float32)
    y = rs.randint(0, 8, (32, 1)).astype(np.int32)

    ff_ref = _build_big_mlp((1, 1, 1, 1), hidden=256)
    ff_tp = _build_big_mlp((2, 4, 1, 1), hidden=256,
                           argv=["--enable-parameter-parallel", "--budget", "8"])
    for ff in (ff_ref, ff_tp):
        ff.fit(x, y, epochs=1, batch_size=16, shuffle=False)
    for lname in ("fc1", "fc2", "head"):
        np.testing.assert_allclose(
            ff_ref.get_weight(lname, "kernel"),
            ff_tp.get_weight(lname, "kernel"), rtol=3e-4, atol=3e-5,
        )


def test_bottleneck_detection():
    sys.argv = ["test"]
    from flexflow_tpu import ActiMode, FFConfig, FFModel
    from flexflow_tpu.search import CostModel, UnitySearch, machine_model_for_mesh
    from flexflow_tpu.machine import MeshShape, build_mesh

    config = FFConfig()
    ff = FFModel(config)
    x = ff.create_tensor((8, 16))
    a = ff.dense(x, 16, name="a")          # bottleneck
    b1 = ff.dense(a, 16, name="b1")        # branch
    b2 = ff.dense(a, 16, name="b2")
    c = ff.add(b1, b2, name="c")           # bottleneck (join)
    d = ff.dense(c, 4, name="d")
    # build PCG without full compile
    from flexflow_tpu import LossType, SGDOptimizer

    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    mesh = ff.mesh
    mm = machine_model_for_mesh(mesh)
    s = UnitySearch(ff.graph, mesh, config, CostModel(mm))
    names = {n.name for n in s.bottlenecks()}
    assert "a" in names and "c" in names
    assert "b1" not in names and "b2" not in names


def test_graph_makespan_fallback_matches_native():
    """The pure-Python fallback and the native ff_eval_makespan implement
    the same model."""
    from flexflow_tpu import native
    from flexflow_tpu.search.cost_model import graph_makespan

    compute = [1.0, 1.0, 1.0, 1.0]
    comm = [0.0, 5.0, 5.0, 0.0]
    src, dst = [0, 0, 1, 2], [1, 2, 3, 3]
    got = graph_makespan(compute, comm, src, dst)
    assert got == pytest.approx(8.0)  # 1 + (1+5) + 1, not sum of branches
    saved, saved_t = native._lib, native._lib_tried
    native._lib, native._lib_tried = None, True
    try:
        assert graph_makespan(compute, comm, src, dst) == pytest.approx(got)
        with pytest.raises(ValueError, match="cycle"):
            graph_makespan([1.0, 1.0], [0.0, 0.0], [0, 1], [1, 0])
    finally:
        native._lib, native._lib_tried = saved, saved_t


def test_two_tower_costed_as_makespan_not_sum():
    """A DLRM-style two-tower graph with comm-heavy parallel branches must
    be costed at max(paths), not the serial sum (VERDICT r2 item 2)."""
    sys.argv = ["test"]
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.search import CostModel, UnitySearch, machine_model_for_mesh

    config = FFConfig()
    config.mesh_axis_sizes = (2, 2, 1, 1)
    config.batch_size = 16
    ff = FFModel(config)
    x = ff.create_tensor((16, 64))
    a = ff.dense(x, 4096, name="mk_stem")
    t1 = ff.dense(a, 4096, name="mk_tower1")
    t2 = ff.dense(a, 4096, name="mk_tower2")
    c = ff.add(t1, t2, name="mk_join")
    ff.softmax(ff.dense(c, 8, name="mk_head"), name="mk_sm")
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    s = UnitySearch(ff.graph, ff.mesh, config,
                    CostModel(machine_model_for_mesh(ff.mesh)))
    # give both towers row-parallel configs (psum comm on each branch)
    choice = {}
    for n in s.order:
        cfgs = s.node_configs(n)
        tp_row = [c_ for c_ in cfgs if c_.name == "tp_row"]
        choice[n.guid] = (tp_row[0] if tp_row and "tower" in n.name
                          else cfgs[0])
    # spy on what evaluate() feeds the accumulator so we can compare the
    # makespan against the old additive evaluator's sum
    from flexflow_tpu.search.cost_model import _MakespanAccum
    rows = []
    orig = _MakespanAccum.add

    class Spy(_MakespanAccum):
        def add(self, guid, compute, comm, comm_axes=(), sync=0.0,
                **kwargs):
            rows.append((guid, compute, comm + sync))
            orig(self, guid, compute, comm, comm_axes=comm_axes, sync=sync,
                 **kwargs)

    import flexflow_tpu.search.unity as unity_mod
    saved = unity_mod._MakespanAccum
    unity_mod._MakespanAccum = Spy
    try:
        cost, _ = s.evaluate(choice)
    finally:
        unity_mod._MakespanAccum = saved
    total_compute = sum(r[1] for r in rows)
    total_comm = sum(r[2] for r in rows)
    assert total_comm > 0  # the tp_row towers do carry psum comm
    # makespan is strictly below the old additive result: the two towers'
    # comm overlaps other work instead of serializing
    assert cost < total_compute + total_comm
    # and it still respects the serialized-compute lower bound
    assert cost >= total_compute - 1e-12


def test_calibration_overrides_roofline():
    """CostModel.calibrate_graph measures the dominant op and the measured
    time replaces the fixed-mfu roofline estimate (VERDICT r2 item 2)."""
    sys.argv = ["test"]
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.search import CostModel, machine_model_for_mesh
    from flexflow_tpu.search.cost_model import _params_key

    config = FFConfig()
    config.mesh_axis_sizes = (1, 1, 1, 1)
    config.batch_size = 8
    ff = FFModel(config)
    x = ff.create_tensor((8, 64))
    t = ff.dense(x, 256, name="cal_fc1")
    ff.softmax(ff.dense(t, 8, name="cal_head"), name="cal_sm")
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    cm = CostModel(machine_model_for_mesh(ff.mesh))
    fc1 = next(n for n in ff.graph.topo_order() if n.name == "cal_fc1")
    before = cm.op_cost(fc1, [((),) * 2], {}, [(8, 64)], [((),) * 2])
    n_measured = cm.calibrate_graph(ff.graph, top_k=1)
    assert n_measured == 1
    assert _params_key(fc1) in cm._calibration
    after = cm.op_cost(fc1, [((),) * 2], {}, [(8, 64)], [((),) * 2])
    meas_fwd, meas_bwd = cm._calibration[_params_key(fc1)]
    # forward and backward are DISTINCT measurements (the reference times
    # both, linear.cc:792-925), not the 2x rule of thumb
    assert meas_fwd > 0 and meas_bwd > 0
    assert meas_bwd != pytest.approx(2.0 * meas_fwd, rel=1e-6)
    assert after.forward_time == pytest.approx(meas_fwd, rel=1e-6)
    assert after.backward_time == pytest.approx(meas_bwd, rel=1e-6)
    assert after.forward_time != pytest.approx(before.forward_time, rel=1e-3)


def test_calibrate_flag_reaches_compile():
    sys.argv = ["test", "--calibrate", "2", "--budget", "2",
                "--enable-parameter-parallel"]
    from flexflow_tpu import ActiMode, FFConfig, FFModel, LossType, SGDOptimizer

    config = FFConfig()
    config.mesh_axis_sizes = (2, 2, 1, 1)
    config.batch_size = 16
    assert config.search_calibrate == 2
    ff = FFModel(config)
    x = ff.create_tensor((16, 32))
    t = ff.dense(x, 64, ActiMode.AC_MODE_RELU, name="cf_fc1")
    ff.softmax(ff.dense(t, 8, name="cf_head"), name="cf_sm")
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    assert ff._compiled


def test_sequence_dp_memoizes_repeated_segments():
    """A deep LM of identical blocks: the sequence DP must hit the segment
    cache on structurally repeated segments and return in bounded time
    (VERDICT r2 item 3; graph.cc:115-180 memoized recursion)."""
    import time

    sys.argv = ["test"]
    from flexflow_tpu import ActiMode, FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.search import CostModel, UnitySearch, machine_model_for_mesh

    config = FFConfig()
    config.mesh_axis_sizes = (2, 2, 1, 1)
    config.batch_size = 16
    config.enable_parameter_parallel = True
    config.base_optimize_threshold = 3
    ff = FFModel(config)
    t = ff.create_tensor((16, 64))
    for i in range(24):
        t = ff.dense(t, 64, ActiMode.AC_MODE_RELU, name=f"dp_l{i}")
    ff.softmax(ff.dense(t, 8, name="dp_head"), name="dp_sm")
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    s = UnitySearch(ff.graph, ff.mesh, config,
                    CostModel(machine_model_for_mesh(ff.mesh)))
    t0 = time.perf_counter()
    choice = s.run()
    elapsed = time.perf_counter() - t0
    assert s.cache_hits > 0, "repeated identical segments must hit the memo"
    assert elapsed < 60.0
    assert len(choice) > 20  # every layer got a config


def test_segment_cache_shared_across_instances():
    """The segment cache can be shared between UnitySearch instances (the
    joint search reuses it across rewritten candidate graphs)."""
    sys.argv = ["test"]
    from flexflow_tpu import ActiMode, FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.search import CostModel, UnitySearch, machine_model_for_mesh

    config = FFConfig()
    config.mesh_axis_sizes = (2, 2, 1, 1)
    config.batch_size = 16
    config.enable_parameter_parallel = True
    config.base_optimize_threshold = 3
    ff = FFModel(config)
    t = ff.create_tensor((16, 64))
    for i in range(8):
        t = ff.dense(t, 64, ActiMode.AC_MODE_RELU, name=f"sc_l{i}")
    ff.softmax(ff.dense(t, 8, name="sc_head"), name="sc_sm")
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    cm = CostModel(machine_model_for_mesh(ff.mesh))
    shared: dict = {}
    s1 = UnitySearch(ff.graph, ff.mesh, config, cm, segment_cache=shared)
    s1.run()
    assert len(shared) > 0
    s2 = UnitySearch(ff.graph, ff.mesh, config, cm, segment_cache=shared)
    s2.run()
    # the second search over the same graph is answered from the memo
    assert s2.cache_hits >= len(shared) // 2


def test_axis_contention_serializes_same_axis_comm():
    """The TPU recast of horizontal machine-resource splits: comm riding the
    SAME ICI axis serializes (link occupancy bound) while disjoint axes
    overlap (graph.cc:267-321 HORIZONTAL splits -> per-axis bounds)."""
    from flexflow_tpu.search.cost_model import graph_makespan

    compute = [0.1, 0.1, 0.1, 0.1]
    comm = [0.0, 5.0, 5.0, 0.0]
    src, dst = [0, 0, 1, 2], [1, 2, 3, 3]
    # branches on the same axis: both all-reduces occupy the same links
    same = graph_makespan(compute, comm, src, dst, axis=[-1, 0, 0, -1])
    # branches on different axes genuinely overlap
    diff = graph_makespan(compute, comm, src, dst, axis=[-1, 0, 1, -1])
    assert same == pytest.approx(10.0)  # 5 + 5 serialized on one axis
    assert diff == pytest.approx(5.3)   # critical path only
    assert diff < same
    # Python fallback agrees
    from flexflow_tpu import native

    saved, saved_t = native._lib, native._lib_tried
    native._lib, native._lib_tried = None, True
    try:
        assert graph_makespan(compute, comm, src, dst,
                              axis=[-1, 0, 0, -1]) == pytest.approx(same)
        assert graph_makespan(compute, comm, src, dst,
                              axis=[-1, 0, 1, -1]) == pytest.approx(diff)
    finally:
        native._lib, native._lib_tried = saved, saved_t


def test_mcmc_legacy_search_never_worse_than_dp():
    """The legacy MCMC strategy search (model.cc:3285-3357 parity) finds a
    strategy at least as good as pure data parallel under the same
    evaluator."""
    sys.argv = ["test", "--budget", "200"]
    from flexflow_tpu import ActiMode, FFConfig, FFModel
    from flexflow_tpu.machine import build_mesh
    from flexflow_tpu.search import CostModel, machine_model_for_mesh
    from flexflow_tpu.search.unity import UnitySearch, mcmc_optimize
    from tests.test_joint_search import _pcg_of

    config = FFConfig()
    config.mesh_axis_sizes = (2, 4, 1, 1)
    config.batch_size = 16
    ff = FFModel(config)
    x = ff.create_tensor((16, 256))
    t = x
    for i in range(3):
        t = ff.dense(t, 2048, ActiMode.AC_MODE_RELU, name=f"mc{i}")
    ff.dense(t, 16, name="mc_head")
    g = _pcg_of(ff)
    mesh = build_mesh(config.mesh_shape())
    cm = CostModel(machine_model_for_mesh(mesh))
    s = UnitySearch(g, mesh, config, cm)

    dp = {n.guid: s.node_configs(n)[0] for n in s.order if s.node_configs(n)}
    t_dp, m_dp = s.evaluate(dp)
    dp_cost = s._memory_penalized(t_dp, m_dp)

    best = mcmc_optimize(s, budget=200)
    t_b, m_b = s.evaluate(best)
    best_cost = s._memory_penalized(t_b, m_b)
    assert best_cost <= dp_cost * 1.0001
    # on this TP-friendly MLP the annealer should actually move off DP
    assert any(cfg.name != "dp" for cfg in best.values())

    # the Strategy-returning entry point works end to end too
    from flexflow_tpu.search import mcmc_search_strategy

    strat = mcmc_search_strategy(g, mesh, config, cost_model=cm)
    assert strat.overrides, "MCMC strategy should move off DP here"


def test_sequence_parallel_config_in_search():
    """The search offers an `sp` (AXIS_SEQ) config for ring-attention nodes
    and seq pass-throughs — round-3 gap: AXIS_SEQ was imported but unused
    by the search."""
    sys.argv = ["test", "--budget", "2"]
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.fftype import OperatorType as OT
    from flexflow_tpu.search import CostModel, UnitySearch, machine_model_for_mesh

    config = FFConfig()
    config.mesh_axis_sizes = (2, 1, 1, 2)  # data=2, seq=2
    config.batch_size = 4
    ff = FFModel(config)
    x = ff.create_tensor((4, 64, 32), name="x")
    a = ff.multihead_attention(x, x, x, 32, 4, causal=True, impl="ring",
                               name="rattn")
    t = ff.layer_norm(a, [2], name="ln")
    ff.dense(t, 8, name="head")
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    s = UnitySearch(ff.graph, ff.mesh, config,
                    CostModel(machine_model_for_mesh(ff.mesh)))
    attn = next(n for n in s.order if n.op_type == OT.OP_MULTIHEAD_ATTENTION)
    names = {c.name for c in s.node_configs(attn)}
    assert "sp" in names, names
    ln = next(n for n in s.order if n.op_type == OT.OP_LAYERNORM)
    assert "sp" in {c.name for c in s.node_configs(ln)}
    # a full-sp choice evaluates (reshard/makespan path handles the layout)
    choice = {}
    for n in s.order:
        cfgs = s.node_configs(n)
        if not cfgs:
            continue
        sp = [c for c in cfgs if c.name == "sp"]
        choice[n.guid] = sp[0] if sp else cfgs[0]
    t_sp, _ = s.evaluate(choice)
    assert t_sp > 0


def test_overlappable_comm_prices_as_max():
    """An overlap-capable op's collective prices as max(compute, comm) +
    fixed overhead in the makespan — not compute + comm — while still
    occupying its ICI axis for the link-occupancy bound."""
    from flexflow_tpu.search.cost_model import _MakespanAccum

    edges = {1: [], 2: []}

    # comm-bound op: comm 2.0 hides the 1.0 compute → 2.0 + 0.1 overhead
    acc = _MakespanAccum()
    acc.add(1, 1.0, 0.0, comm_axes=("seq",), overlappable_comm=2.0,
            overlap_overhead=0.1)
    assert np.isclose(acc.makespan(edges), 2.1)

    # compute-bound op: compute 3.0 hides the 2.0 comm → 3.0 + 0.1
    acc = _MakespanAccum()
    acc.add(1, 3.0, 0.0, comm_axes=("seq",), overlappable_comm=2.0,
            overlap_overhead=0.1)
    assert np.isclose(acc.makespan(edges), 3.1)

    # the serial twin of the first case pays compute + comm
    acc = _MakespanAccum()
    acc.add(1, 1.0, 2.0, comm_axes=("seq",))
    assert np.isclose(acc.makespan(edges), 3.0)

    # per-axis occupancy: overlapped traffic still serializes against
    # OTHER comm on the same axis — two overlapped ops on one axis are
    # bounded by their combined link time even when each hides behind
    # its own (parallel-branch) compute
    acc = _MakespanAccum()
    acc.add(1, 1.0, 0.0, comm_axes=("seq",), overlappable_comm=4.0)
    acc.add(2, 1.0, 0.0, comm_axes=("seq",), overlappable_comm=4.0)
    assert acc.makespan(edges) >= 8.0


def test_overlap_pricing_flips_search_to_ring_sp():
    """The acceptance scenario for the overlap-aware cost model: a
    long-seq graph + an ICI bandwidth where the ring's communication is
    ~74% of the dp attention compute. Serial pricing (compute + comm)
    rejects the sequence-parallel ring strategy — the hops land ON TOP of
    the (4× smaller) sharded compute, pushing past the dp price — while
    overlap pricing (max(compute, comm), matching the double-buffered
    runtime schedule) selects it. Same graph, same machine, same
    measurements: only the pricing rule differs."""
    sys.argv = ["test"]
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.fftype import OperatorType as OT
    from flexflow_tpu.search import CostModel, UnitySearch
    from flexflow_tpu.search.cost_model import _shard_elems, dtype_bytes
    from flexflow_tpu.search.machine_model import CHIPS, TPUMachineModel
    from dataclasses import replace

    config = FFConfig()
    config.mesh_axis_sizes = (1, 1, 1, 4)  # seq=4 (long-context: batch 1)
    config.batch_size = 1
    config.enable_sample_parallel = True
    ff = FFModel(config)
    x = ff.create_tensor((1, 4096, 64), name="x")
    a = ff.multihead_attention(x, x, x, 64, 4, causal=True, impl="ring",
                               name="rattn")
    t = ff.layer_norm(a, [2], name="ln")
    ff.dense(t, 8, name="head")
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    n_seq = 4
    axis_sizes = {k: int(v) for k, v in ff.mesh.shape.items()}

    def search_for(machine, overlap: bool):
        config.overlap_collectives = overlap
        return UnitySearch(ff.graph, ff.mesh, config, CostModel(machine))

    # 1) price the dp attention (fwd+bwd) on a probe machine — ICI
    #    bandwidth does not enter op_cost, so this is the real C_dp
    probe = TPUMachineModel(CHIPS["v5e"], axis_sizes)
    s_probe = search_for(probe, True)
    attn = next(n for n in s_probe.order
                if n.op_type == OT.OP_MULTIHEAD_ATTENTION)
    dp_cfg = next(c for c in s_probe.node_configs(attn) if c.name == "dp")
    in_shapes = [tuple(d.size for d in pt.shape.dims
                       if not d.is_replica_dim) for pt in attn.inputs]
    cmx = s_probe.cm.op_cost(
        attn, [dp_cfg.out_assign], dict(dp_cfg.weight_specs),
        in_shapes, [dp_cfg.out_assign] * len(in_shapes))
    c_dp = cmx.forward_time + cmx.backward_time

    # 2) solve the ICI bandwidth that puts the ring comm at 0.85·C_dp:
    #    ring = 3 · 2(n−1) · (local_bytes/bw + lat)
    out_pt = attn.outputs[0]
    shape = tuple(d.size for d in out_pt.shape.dims if not d.is_replica_dim)
    sp_cfg = next(c for c in s_probe.node_configs(attn) if c.name == "sp")
    local_bytes = _shard_elems(shape, sp_cfg.out_assign, axis_sizes) \
        * dtype_bytes(out_pt.dtype)
    hops = 3.0 * 2 * (n_seq - 1)
    lat = 1e-7
    per_hop_target = 0.74 * c_dp / hops
    assert per_hop_target > lat
    bw = local_bytes / (per_hop_target - lat)
    chip = replace(CHIPS["v5e"], ici_bandwidth=bw, ici_latency=lat)
    machine = TPUMachineModel(chip, axis_sizes)

    def cost_of(s, want):
        choice = {}
        for n in s.order:
            cfgs = s.node_configs(n)
            if not cfgs:
                continue
            named = [c for c in cfgs if c.name == want]
            choice[n.guid] = named[0] if named else cfgs[0]
        t, mem = s.evaluate(choice)
        return s._memory_penalized(t, mem)

    # serial pricing: the ring strategy LOSES to dp...
    s_serial = search_for(machine, overlap=False)
    assert cost_of(s_serial, "sp") > cost_of(s_serial, "dp")
    best_serial = s_serial.run()
    assert best_serial[attn.guid].name != "sp"

    # ...overlap pricing: the SAME strategy on the SAME machine wins,
    # and the search selects it
    s_overlap = search_for(machine, overlap=True)
    assert cost_of(s_overlap, "sp") < cost_of(s_overlap, "dp")
    best_overlap = s_overlap.run()
    assert best_overlap[attn.guid].name == "sp"
    config.overlap_collectives = True


def test_ppermute_hop_calibration_roundtrip(tmp_path):
    """calibrate_collectives measures the real ppermute hop on the mesh
    (two payloads, two-point slope), collective_rotate serves the fitted
    hop, and the entry persists per device kind through the warm-start
    calibration DB like any op measurement."""
    from flexflow_tpu.machine import MeshShape, build_mesh
    from flexflow_tpu.search import CostModel
    from flexflow_tpu.search.machine_model import machine_model_for_mesh
    from flexflow_tpu.warmstart.calibration_db import CalibrationDB

    mesh = build_mesh(MeshShape((1, 1, 4, 1),
                                ("data", "model", "seq", "pipe")))
    cm = CostModel(machine_model_for_mesh(mesh))
    analytic = cm.collective_rotate(262144, "seq")
    assert analytic == cm.machine.rotate(262144, "seq")  # no measurement yet
    assert cm.calibrate_collectives(mesh, ["seq"]) == 1
    measured = cm.collective_rotate(262144, "seq")
    assert measured > 0
    # monotone in bytes, with a non-negative intercept
    assert cm.collective_rotate(2 * 262144, "seq") >= measured
    # size-1 axes are not measurable — left analytic, not crashed
    assert cm.calibrate_collectives(mesh, ["model"]) == 0

    db = CalibrationDB(str(tmp_path))
    db.save_from(cm)
    cm2 = CostModel(machine_model_for_mesh(mesh))
    db.load_into(cm2)
    assert cm2.collective_rotate(262144, "seq") == pytest.approx(measured)
    # a warm DB re-calibrates nothing (the cached entry wins)
    assert cm2.calibrate_collectives(mesh, ["seq"]) == 0
