"""Serving-engine tests (serving/, docs/serving.md).

The acceptance surface of the decode-graph + continuous-batching
subsystem, on the CPU mesh (the decode attention op routes through the
reference einsum there, so everything below is Pallas-free except the
kernel-parity test, which the conftest capability probe converts to a
clean skip on environment gaps):

  - greedy decode is token-identical to the teacher-forced training
    forward's argmax at every generated position;
  - an interleaved continuous batch (requests admitted/evicted mid-run)
    is token-identical to serving each request alone;
  - the KV cache round-trips a tensor-parallel mesh: a head-parallel plan
    shards the cache feature dim over `model` and decode stays
    token-identical to the single-device engine;
  - EOS / max_new_tokens / cache-capacity completion all fire with the
    right reasons;
  - a second serving compile of the same (model, slots, max_seq, mesh)
    against one --warmstart-dir is a plan-cache hit: ZERO
    UnitySearch.evaluate calls, zero joint_graph_optimize calls.
"""

import sys

import numpy as np
import pytest


def _lm_config():
    from flexflow_tpu.models import TransformerLMConfig

    return TransformerLMConfig(
        vocab_size=64, hidden_size=32, num_heads=4, num_layers=2,
        sequence_length=32, attention_impl="xla")


def _build_lm(mesh=(1, 1, 1, 1), batch=8, argv=()):
    sys.argv = ["test"] + list(argv)
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import build_transformer_lm

    cfg = FFConfig()
    if cfg.mesh_axis_sizes is None:
        cfg.mesh_axis_sizes = mesh
    cfg.batch_size = batch
    ff = FFModel(cfg)
    build_transformer_lm(ff, _lm_config(), batch_size=batch)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff


def _teacher_argmax(ff, sequence):
    """Training-graph forward over `sequence`; argmax at every position."""
    import jax

    T = len(sequence)
    toks = np.asarray(sequence, np.int32)[None, :]
    pos = np.arange(T, dtype=np.int32)[None, :]
    fwd = ff.executor._forward_fn or ff.executor.build_forward()
    xs = ff.executor.shard_batch({"tokens": toks, "positions": pos}, {})
    logits, _ = fwd(ff._params, ff._state, xs, False)
    return np.asarray(jax.device_get(logits)).argmax(-1)[0]


PROMPTS = [[3, 7, 11, 2, 5], [5, 2], [1, 9, 30, 30, 12, 4, 8], [60, 1, 2]]


def test_greedy_decode_parity_vs_teacher_forced():
    """Every greedy-decoded token equals the training forward's argmax at
    that position, for prompts long and short of the prefill chunk (so
    both the bucketed prefill and the q=1 decode path are checked)."""
    ff = _build_lm(batch=1)
    eng = ff.serve(slots=2, max_new_tokens=8, prefill_chunk=4)
    for prompt in PROMPTS:
        (gen,) = eng.generate([prompt])
        assert len(gen) == 8
        seq = prompt + gen
        am = _teacher_argmax(ff, seq)
        want = am[len(prompt) - 1 : len(seq) - 1].tolist()
        assert gen == want, f"prompt {prompt}: decode {gen} != teacher {want}"


def test_continuous_batching_invariance():
    """Interleaved batch == sequential single-request runs, token for
    token. Five requests through two slots forces mid-run admission and
    slot reuse (stale cache rows from the previous resident must never
    leak into the next request)."""
    ff = _build_lm(batch=1)
    prompts = PROMPTS + [[2, 4, 6, 8]]

    eng = ff.serve(slots=2, max_new_tokens=6, prefill_chunk=4)
    interleaved = eng.generate(prompts)
    assert eng.scheduler.drained
    # two slots, five requests: admissions happened while others decoded
    assert eng.stats()["requests_completed"] == 5

    solo_eng = ff.serve(slots=2, max_new_tokens=6, prefill_chunk=4)
    solo = [solo_eng.generate([p])[0] for p in prompts]
    assert interleaved == solo


def test_kv_cache_sharding_roundtrip_tp_mesh():
    """A head-parallel decode plan on a (data=2, model=2) mesh — QKV/O
    sharded, KV cache feature dim over `model`, slot dim over `data` —
    produces token-identical output to the single-device engine, and the
    cache state actually carries the sharded spec."""
    from jax.sharding import PartitionSpec as P

    ff = _build_lm(mesh=(2, 2, 1, 1), batch=8)
    strat = {}
    for i in range(2):
        strat[f"l{i}_attn"] = {"outputs": {}, "weights": {
            "wq": P(None, "model"), "wk": P(None, "model"),
            "wv": P(None, "model"),
            "bq": P("model"), "bk": P("model"), "bv": P("model"),
            "wo": P("model", None), "bo": P(),
            "cache_k": P("data", None, "model"),
            "cache_v": P("data", None, "model"),
        }}
    eng = ff.serve(slots=4, max_new_tokens=5, prefill_chunk=4,
                   strategy=strat)
    assert eng.decode_model._plan_source == "manual"
    ck = eng.decode_model._state["l0_attn"]["cache_k"]
    assert ck.sharding.spec == P("data", None, "model")
    # 4 slots over data=2: the slot dim is genuinely sharded too
    assert ck.sharding.shard_shape(ck.shape)[0] == 2
    sharded = eng.generate(PROMPTS[:2])

    ff1 = _build_lm(mesh=(1, 1, 1, 1), batch=1)
    eng1 = ff1.serve(slots=4, max_new_tokens=5, prefill_chunk=4)
    assert eng1.generate(PROMPTS[:2]) == sharded


def test_eos_and_max_len_completion():
    """All three completion rules: eos (stop token sampled), max_tokens
    (budget), and length (KV cache full)."""
    ff = _build_lm(batch=1)
    eng = ff.serve(slots=2, max_new_tokens=10, prefill_chunk=4)
    prompt = PROMPTS[0]
    # discover what greedy generates, then replay with its second token
    # as the stop token
    (gen,) = eng.generate([prompt])
    eos = gen[1]
    req = eng.submit(prompt, eos_id=eos)
    eng.run_until_drained()
    assert req.finished and req.finish_reason == "eos"
    assert req.generated[-1] == eos and len(req.generated) == 2

    req2 = eng.submit(prompt, max_new_tokens=3)
    eng.run_until_drained()
    assert req2.finish_reason == "max_tokens"
    assert len(req2.generated) == 3 and req2.generated == gen[:3]

    # cache capacity: prompt of 6 into an 8-row cache leaves room to feed
    # 2 generated tokens back; the 3rd sampled token cannot be fed
    small = ff.serve(slots=2, max_new_tokens=10, prefill_chunk=4,
                     max_seq_len=8)
    req3 = small.submit([1, 2, 3, 4, 5, 6])
    small.run_until_drained()
    assert req3.finish_reason == "length"
    assert len(req3.generated) == 3
    # oversized prompts are rejected at submission
    with pytest.raises(ValueError):
        small.submit(list(range(9)))


class _SearchSpy:
    """Counts UnitySearch.evaluate + joint_graph_optimize calls (the
    test_warmstart.py hook, reused for the serving acceptance check)."""

    def __enter__(self):
        import flexflow_tpu.search.joint as joint
        import flexflow_tpu.search.unity as unity

        self.evals = 0
        self.searches = 0
        self._unity, self._joint = unity, joint
        self._orig_eval = unity.UnitySearch.evaluate
        self._orig_opt = joint.joint_graph_optimize
        spy = self

        def eval_spy(us, *a, **kw):
            spy.evals += 1
            return spy._orig_eval(us, *a, **kw)

        def opt_spy(*a, **kw):
            spy.searches += 1
            return spy._orig_opt(*a, **kw)

        unity.UnitySearch.evaluate = eval_spy
        joint.joint_graph_optimize = opt_spy
        return self

    def __exit__(self, *exc):
        self._unity.UnitySearch.evaluate = self._orig_eval
        self._joint.joint_graph_optimize = self._orig_opt
        return False


def test_serving_warmstart_plan_cache_hit(tmp_path):
    """Second serving compile of the same (model, slots, max_seq, mesh)
    against one --warmstart-dir: plan_source=cache, 0 evaluate calls,
    0 searches, and token-identical output (the acceptance criterion)."""
    ws = str(tmp_path / "ws")
    ff = _build_lm(mesh=(2, 4, 1, 1), batch=8,
                   argv=["--only-data-parallel"])
    ov = dict(only_data_parallel=False, search_budget=4,
              enable_parameter_parallel=True,
              enable_attribute_parallel=True, warmstart_dir=ws)
    kw = dict(slots=8, max_new_tokens=4, prefill_chunk=4,
              config_overrides=ov)
    eng1 = ff.serve(**kw)
    assert eng1.decode_model._plan_source == "search"
    out1 = eng1.generate(PROMPTS[:2])

    with _SearchSpy() as spy:
        eng2 = ff.serve(**kw)
    assert spy.searches == 0, "serving plan-cache hit must not re-search"
    assert spy.evals == 0, "serving plan-cache hit must cost 0 evaluations"
    assert eng2.decode_model._plan_source == "cache"
    assert eng2.generate(PROMPTS[:2]) == out1

    # a different bucket geometry (slots) is a different decode graph —
    # it must NOT be served by the cached plan
    with _SearchSpy() as spy:
        eng3 = ff.serve(slots=4, max_new_tokens=4, prefill_chunk=4,
                        config_overrides=ov)
    assert eng3.decode_model._plan_source == "search"
    assert spy.searches == 1


def test_serving_telemetry_artifacts(tmp_path):
    """With a telemetry session attached, serving emits the serve.compile
    event (plan_source), per-request serve.request events with TTFT, and
    a serve.summary with requests/s/chip + decode tokens/s/chip."""
    ff = _build_lm(batch=1)
    ff.enable_telemetry(str(tmp_path / "tel"))
    eng = ff.serve(slots=2, max_new_tokens=4, prefill_chunk=4)
    eng.generate(PROMPTS[:3])
    eng.telemetry.close()

    from flexflow_tpu.telemetry import read_jsonl

    recs = read_jsonl(str(tmp_path / "tel" / "metrics.jsonl"))
    compiles = [r for r in recs if r["kind"] == "serve.compile"]
    assert compiles and compiles[0]["plan_source"] == "default"
    assert compiles[0]["slots"] == 2
    reqs = [r for r in recs if r["kind"] == "serve.request"]
    assert len(reqs) == 3
    for r in reqs:
        assert r["ttft_s"] > 0 and r["new_tokens"] == 4
        assert r["finish_reason"] == "max_tokens"
    summaries = [r for r in recs if r["kind"] == "serve.summary"]
    assert summaries
    s = summaries[-1]
    assert s["requests_per_sec_per_chip"] > 0
    assert s["decode_tokens_per_sec_per_chip"] > 0
    assert s["requests_completed"] == 3

    import json

    with open(tmp_path / "tel" / "trace.json") as f:
        names = {e["name"] for e in json.load(f)["traceEvents"]}
    for span in ("serve.compile", "serve.prefill", "serve.step"):
        assert span in names, f"trace missing {span!r}"


def test_model_zoo_decode_builder_matches_replay():
    """models.build_transformer_lm_decode expresses the same decode graph
    the serving replay derives: same node names, op types, and KV-cache
    shapes — the zoo can build the decode graph without forking the
    training definition."""
    sys.argv = ["test"]
    from flexflow_tpu import CompMode, FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.fftype import OperatorType as OT
    from flexflow_tpu.models import build_transformer_lm_decode
    from flexflow_tpu.serving import ServingSpec, build_decode_model

    c = _lm_config()
    ff = _build_lm(batch=1)
    dec, max_seq = build_decode_model(ff, ServingSpec(slots=2))
    assert max_seq == c.sequence_length

    cfg = FFConfig()
    cfg.mesh_axis_sizes = (1, 1, 1, 1)
    zoo = FFModel(cfg)
    build_transformer_lm_decode(zoo, c, slots=2)
    zoo.compile(optimizer=SGDOptimizer(lr=0.0),
                loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                comp_mode=CompMode.COMP_MODE_INFERENCE)

    def sig(model):
        return [(n.name, n.op_type.name,
                 tuple(tuple(ws.shape) for ws in n.weight_specs
                       if not ws.trainable))
                for n in model.graph.topo_order()]

    assert sig(zoo) == sig(dec)
    attn = [n for n in zoo.graph.topo_order()
            if n.op_type == OT.OP_INC_MULTIHEAD_ATTENTION]
    assert len(attn) == c.num_layers
    cache = next(ws for ws in attn[0].weight_specs if not ws.trainable)
    assert cache.shape == (2, c.sequence_length + 1, c.hidden_size)


def test_flash_decode_kernel_matches_reference():
    """The Pallas single-query decode kernel (interpret mode on CPU)
    matches the einsum reference across partial/full/one-token cache
    fills. Converted to a clean skip by the conftest capability probe
    when the environment lacks the Pallas APIs."""
    import jax.numpy as jnp

    from flexflow_tpu.kernels.flash_attention import (
        decode_attention_reference,
        flash_decode_attention,
    )

    rs = np.random.RandomState(0)
    slots, S, H, hd = 3, 256, 2, 64
    E = H * hd
    q = jnp.asarray(rs.randn(slots, 1, E), jnp.float32)
    k = jnp.asarray(rs.randn(slots, S, E), jnp.float32)
    v = jnp.asarray(rs.randn(slots, S, E), jnp.float32)
    lengths = jnp.asarray([1, 100, 256], jnp.int32)
    ref = decode_attention_reference(q, k, v, (lengths - 1)[:, None],
                                     num_heads=H)
    out = flash_decode_attention(q, k, v, lengths, num_heads=H,
                                 block_k=128, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
