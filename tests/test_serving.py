"""Serving-engine tests (serving/, docs/serving.md).

The acceptance surface of the decode-graph + continuous-batching
subsystem, on the CPU mesh (the decode attention op routes through the
reference einsum there, so everything below is Pallas-free except the
kernel-parity test, which the conftest capability probe converts to a
clean skip on environment gaps):

  - greedy decode is token-identical to the teacher-forced training
    forward's argmax at every generated position;
  - an interleaved continuous batch (requests admitted/evicted mid-run)
    is token-identical to serving each request alone;
  - the KV cache round-trips a tensor-parallel mesh: a head-parallel plan
    shards the cache feature dim over `model` and decode stays
    token-identical to the single-device engine;
  - EOS / max_new_tokens / cache-capacity completion all fire with the
    right reasons;
  - a second serving compile of the same (model, slots, max_seq, mesh)
    against one --warmstart-dir is a plan-cache hit: ZERO
    UnitySearch.evaluate calls, zero joint_graph_optimize calls.
"""

import sys

import numpy as np
import pytest


def _lm_config():
    from flexflow_tpu.models import TransformerLMConfig

    return TransformerLMConfig(
        vocab_size=64, hidden_size=32, num_heads=4, num_layers=2,
        sequence_length=32, attention_impl="xla")


def _build_lm(mesh=(1, 1, 1, 1), batch=8, argv=()):
    sys.argv = ["test"] + list(argv)
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import build_transformer_lm

    cfg = FFConfig()
    if cfg.mesh_axis_sizes is None:
        cfg.mesh_axis_sizes = mesh
    cfg.batch_size = batch
    ff = FFModel(cfg)
    build_transformer_lm(ff, _lm_config(), batch_size=batch)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff


def _teacher_argmax(ff, sequence):
    """Training-graph forward over `sequence`; argmax at every position."""
    import jax

    T = len(sequence)
    toks = np.asarray(sequence, np.int32)[None, :]
    pos = np.arange(T, dtype=np.int32)[None, :]
    fwd = ff.executor._forward_fn or ff.executor.build_forward()
    xs = ff.executor.shard_batch({"tokens": toks, "positions": pos}, {})
    logits, _ = fwd(ff._params, ff._state, xs, False)
    return np.asarray(jax.device_get(logits)).argmax(-1)[0]


PROMPTS = [[3, 7, 11, 2, 5], [5, 2], [1, 9, 30, 30, 12, 4, 8], [60, 1, 2]]


def test_greedy_decode_parity_vs_teacher_forced():
    """Every greedy-decoded token equals the training forward's argmax at
    that position, for prompts long and short of the prefill chunk (so
    both the bucketed prefill and the q=1 decode path are checked)."""
    ff = _build_lm(batch=1)
    eng = ff.serve(slots=2, max_new_tokens=8, prefill_chunk=4)
    for prompt in PROMPTS:
        (gen,) = eng.generate([prompt])
        assert len(gen) == 8
        seq = prompt + gen
        am = _teacher_argmax(ff, seq)
        want = am[len(prompt) - 1 : len(seq) - 1].tolist()
        assert gen == want, f"prompt {prompt}: decode {gen} != teacher {want}"


def test_continuous_batching_invariance():
    """Interleaved batch == sequential single-request runs, token for
    token. Five requests through two slots forces mid-run admission and
    slot reuse (stale cache rows from the previous resident must never
    leak into the next request)."""
    ff = _build_lm(batch=1)
    prompts = PROMPTS + [[2, 4, 6, 8]]

    eng = ff.serve(slots=2, max_new_tokens=6, prefill_chunk=4)
    interleaved = eng.generate(prompts)
    assert eng.scheduler.drained
    # two slots, five requests: admissions happened while others decoded
    assert eng.stats()["requests_completed"] == 5

    solo_eng = ff.serve(slots=2, max_new_tokens=6, prefill_chunk=4)
    solo = [solo_eng.generate([p])[0] for p in prompts]
    assert interleaved == solo


def test_kv_cache_sharding_roundtrip_tp_mesh():
    """A head-parallel decode plan on a (data=2, model=2) mesh — QKV/O
    sharded, KV cache feature dim over `model` — produces token-identical
    output to the single-device engine for BOTH layouts, and the cache
    state actually carries the sharded spec (contiguous: slot dim over
    `data` too; paged: the pool's block dim stays whole — blocks are
    shared across slots by prefix reuse)."""
    from jax.sharding import PartitionSpec as P

    def attn_strategy(cache_weights):
        strat = {}
        for i in range(2):
            strat[f"l{i}_attn"] = {"outputs": {}, "weights": {
                "wq": P(None, "model"), "wk": P(None, "model"),
                "wv": P(None, "model"),
                "bq": P("model"), "bk": P("model"), "bv": P("model"),
                "wo": P("model", None), "bo": P(),
                **cache_weights,
            }}
        return strat

    ff1 = _build_lm(mesh=(1, 1, 1, 1), batch=1)
    eng1 = ff1.serve(slots=4, max_new_tokens=5, prefill_chunk=4)
    want = eng1.generate(PROMPTS[:2])

    ff = _build_lm(mesh=(2, 2, 1, 1), batch=8)
    eng = ff.serve(slots=4, max_new_tokens=5, prefill_chunk=4,
                   strategy=attn_strategy({
                       "pool_k": P(None, None, "model"),
                       "pool_v": P(None, None, "model")}))
    assert eng.decode_model._plan_source == "manual"
    pk = eng.decode_model._state["l0_attn"]["pool_k"]
    assert pk.sharding.spec == P(None, None, "model")
    # feature dim over model=2: each chip holds only its heads' pool
    assert pk.sharding.shard_shape(pk.shape)[-1] == pk.shape[-1] // 2
    assert eng.generate(PROMPTS[:2]) == want

    engc = ff.serve(slots=4, max_new_tokens=5, prefill_chunk=4,
                    kv_layout="contiguous",
                    strategy=attn_strategy({
                        "cache_k": P("data", None, "model"),
                        "cache_v": P("data", None, "model")}))
    ck = engc.decode_model._state["l0_attn"]["cache_k"]
    assert ck.sharding.spec == P("data", None, "model")
    # 4 slots over data=2: the contiguous slot dim is genuinely sharded
    assert ck.sharding.shard_shape(ck.shape)[0] == 2
    assert engc.generate(PROMPTS[:2]) == want


def test_eos_and_max_len_completion():
    """All three completion rules: eos (stop token sampled), max_tokens
    (budget), and length (KV cache full)."""
    ff = _build_lm(batch=1)
    eng = ff.serve(slots=2, max_new_tokens=10, prefill_chunk=4)
    prompt = PROMPTS[0]
    # discover what greedy generates, then replay with its second token
    # as the stop token
    (gen,) = eng.generate([prompt])
    eos = gen[1]
    req = eng.submit(prompt, eos_id=eos)
    eng.run_until_drained()
    assert req.finished and req.finish_reason == "eos"
    assert req.generated[-1] == eos and len(req.generated) == 2

    req2 = eng.submit(prompt, max_new_tokens=3)
    eng.run_until_drained()
    assert req2.finish_reason == "max_tokens"
    assert len(req2.generated) == 3 and req2.generated == gen[:3]

    # cache capacity: prompt of 6 into an 8-row cache leaves room to feed
    # 2 generated tokens back; the 3rd sampled token cannot be fed
    small = ff.serve(slots=2, max_new_tokens=10, prefill_chunk=4,
                     max_seq_len=8)
    req3 = small.submit([1, 2, 3, 4, 5, 6])
    small.run_until_drained()
    assert req3.finish_reason == "length"
    assert len(req3.generated) == 3
    # oversized prompts are rejected at submission
    with pytest.raises(ValueError):
        small.submit(list(range(9)))


class _SearchSpy:
    """Counts UnitySearch.evaluate + joint_graph_optimize calls (the
    test_warmstart.py hook, reused for the serving acceptance check)."""

    def __enter__(self):
        import flexflow_tpu.search.joint as joint
        import flexflow_tpu.search.unity as unity

        self.evals = 0
        self.searches = 0
        self._unity, self._joint = unity, joint
        self._orig_eval = unity.UnitySearch.evaluate
        self._orig_opt = joint.joint_graph_optimize
        spy = self

        def eval_spy(us, *a, **kw):
            spy.evals += 1
            return spy._orig_eval(us, *a, **kw)

        def opt_spy(*a, **kw):
            spy.searches += 1
            return spy._orig_opt(*a, **kw)

        unity.UnitySearch.evaluate = eval_spy
        joint.joint_graph_optimize = opt_spy
        return self

    def __exit__(self, *exc):
        self._unity.UnitySearch.evaluate = self._orig_eval
        self._joint.joint_graph_optimize = self._orig_opt
        return False


def test_serving_warmstart_plan_cache_hit(tmp_path):
    """Second serving compile of the same (model, slots, max_seq, mesh)
    against one --warmstart-dir: plan_source=cache, 0 evaluate calls,
    0 searches, and token-identical output (the acceptance criterion)."""
    ws = str(tmp_path / "ws")
    ff = _build_lm(mesh=(2, 4, 1, 1), batch=8,
                   argv=["--only-data-parallel"])
    ov = dict(only_data_parallel=False, search_budget=4,
              enable_parameter_parallel=True,
              enable_attribute_parallel=True, warmstart_dir=ws)
    kw = dict(slots=8, max_new_tokens=4, prefill_chunk=4,
              config_overrides=ov)
    eng1 = ff.serve(**kw)
    assert eng1.decode_model._plan_source == "search"
    out1 = eng1.generate(PROMPTS[:2])

    with _SearchSpy() as spy:
        eng2 = ff.serve(**kw)
    assert spy.searches == 0, "serving plan-cache hit must not re-search"
    assert spy.evals == 0, "serving plan-cache hit must cost 0 evaluations"
    assert eng2.decode_model._plan_source == "cache"
    assert eng2.generate(PROMPTS[:2]) == out1

    # a different bucket geometry (slots) is a different decode graph —
    # it must NOT be served by the cached plan
    with _SearchSpy() as spy:
        eng3 = ff.serve(slots=4, max_new_tokens=4, prefill_chunk=4,
                        config_overrides=ov)
    assert eng3.decode_model._plan_source == "search"
    assert spy.searches == 1


def test_serving_telemetry_artifacts(tmp_path):
    """With a telemetry session attached, serving emits the serve.compile
    event (plan_source), per-request serve.request events with TTFT, and
    a serve.summary with requests/s/chip + decode tokens/s/chip."""
    ff = _build_lm(batch=1)
    ff.enable_telemetry(str(tmp_path / "tel"))
    eng = ff.serve(slots=2, max_new_tokens=4, prefill_chunk=4)
    eng.generate(PROMPTS[:3])
    eng.telemetry.close()

    from flexflow_tpu.telemetry import read_jsonl

    recs = read_jsonl(str(tmp_path / "tel" / "metrics.jsonl"))
    compiles = [r for r in recs if r["kind"] == "serve.compile"]
    assert compiles and compiles[0]["plan_source"] == "default"
    assert compiles[0]["slots"] == 2
    reqs = [r for r in recs if r["kind"] == "serve.request"]
    assert len(reqs) == 3
    for r in reqs:
        assert r["ttft_s"] > 0 and r["new_tokens"] == 4
        assert r["finish_reason"] == "max_tokens"
    summaries = [r for r in recs if r["kind"] == "serve.summary"]
    assert summaries
    s = summaries[-1]
    assert s["requests_per_sec_per_chip"] > 0
    assert s["decode_tokens_per_sec_per_chip"] > 0
    assert s["requests_completed"] == 3

    import json

    with open(tmp_path / "tel" / "trace.json") as f:
        names = {e["name"] for e in json.load(f)["traceEvents"]}
    for span in ("serve.compile", "serve.prefill", "serve.step"):
        assert span in names, f"trace missing {span!r}"


@pytest.mark.parametrize("layout", ["paged", "contiguous"])
def test_model_zoo_decode_builder_matches_replay(layout):
    """models.build_transformer_lm_decode expresses the same decode graph
    the serving replay derives — for BOTH KV layouts: same node names, op
    types, and cache/pool shapes — the zoo can build the decode graph
    without forking the training definition."""
    sys.argv = ["test"]
    from flexflow_tpu import CompMode, FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.fftype import OperatorType as OT
    from flexflow_tpu.models import build_transformer_lm_decode
    from flexflow_tpu.serving import ServingSpec, build_decode_model

    c = _lm_config()
    ff = _build_lm(batch=1)
    dec, max_seq = build_decode_model(
        ff, ServingSpec(slots=2, kv_layout=layout))
    assert max_seq == c.sequence_length

    cfg = FFConfig()
    cfg.mesh_axis_sizes = (1, 1, 1, 1)
    zoo = FFModel(cfg)
    build_transformer_lm_decode(zoo, c, slots=2, kv_layout=layout)
    zoo.compile(optimizer=SGDOptimizer(lr=0.0),
                loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                comp_mode=CompMode.COMP_MODE_INFERENCE)

    def sig(model):
        return [(n.name, n.op_type.name,
                 tuple(tuple(ws.shape) for ws in n.weight_specs
                       if not ws.trainable))
                for n in model.graph.topo_order()]

    assert sig(zoo) == sig(dec)
    if layout == "paged":
        attn = [n for n in zoo.graph.topo_order()
                if n.op_type == OT.OP_PAGED_INC_MULTIHEAD_ATTENTION]
        assert len(attn) == c.num_layers
        pool = next(ws for ws in attn[0].weight_specs if not ws.trainable)
        # capacity parity + scratch: slots * ceil(max_seq/bs) + 1 blocks
        bs = cfg.serve_kv_block_size
        assert pool.shape == (2 * (c.sequence_length // bs) + 1, bs,
                              c.hidden_size)
    else:
        attn = [n for n in zoo.graph.topo_order()
                if n.op_type == OT.OP_INC_MULTIHEAD_ATTENTION]
        assert len(attn) == c.num_layers
        cache = next(ws for ws in attn[0].weight_specs if not ws.trainable)
        assert cache.shape == (2, c.sequence_length + 1, c.hidden_size)


# ===================================================================== paged
# The paged-KV matrix (ISSUE 11): token identity with the contiguous
# layout across prompt shapes and slot reuse, COW divergence after a
# shared prefix, refcount-exact reclamation, chunked-prefill interleaving,
# the reserved scratch block, and the layout-keyed warm-start fingerprint.


def test_paged_token_identical_to_contiguous():
    """The full continuous-batching run — ragged prompts, mid-run
    admission, slot reuse — is token-identical between the paged and
    contiguous layouts (the tentpole acceptance criterion)."""
    ff = _build_lm(batch=1)
    prompts = PROMPTS + [[2, 4, 6, 8]]
    paged = ff.serve(slots=2, max_new_tokens=6, prefill_chunk=4,
                     kv_layout="paged")
    assert paged.block_manager is not None
    out_paged = paged.generate(prompts)
    contig = ff.serve(slots=2, max_new_tokens=6, prefill_chunk=4,
                      kv_layout="contiguous")
    assert contig.block_manager is None
    assert out_paged == contig.generate(prompts)
    # every completed request released its blocks exactly
    assert paged.block_manager.blocks_in_use == 0
    paged.block_manager.check_invariants()


def test_paged_cow_divergence_after_shared_prefix():
    """Two prompts sharing a prefix past block granularity: the second
    admission maps the shared blocks (prefix hit), the first divergent
    write copies exactly the block it lands in (COW), and both token
    streams stay identical to the contiguous engine's."""
    ff = _build_lm(batch=1)
    # 6 shared tokens @ bs=4: one full block + a registered PARTIAL tail;
    # the second prompt extends the prefix INSIDE that partial block, so
    # its first tail write must COW it
    shared = [3, 7, 11, 2, 5, 9]
    prompts = [list(shared), shared + [31, 32]]
    eng = ff.serve(slots=2, max_new_tokens=5, prefill_chunk=4,
                   kv_layout="paged", kv_block_size=4)
    out = eng.generate(prompts)
    st = eng.block_manager.stats
    assert st.prefix_hits >= 1, "second prompt must share the prefix"
    assert st.shared_tokens >= len(shared)
    assert st.cow_copies >= 1, \
        "divergence inside a shared block must copy-on-write"
    contig = ff.serve(slots=2, max_new_tokens=5, prefill_chunk=4,
                      kv_layout="contiguous")
    assert out == contig.generate(prompts)

    # identical block-aligned prompts too (the N-users-one-system-prompt
    # case): the whole prompt is shared; only the final token is
    # recomputed and its write COWs the one block it lands in
    shared8 = [3, 7, 11, 2, 5, 9, 13, 1]  # 2 full blocks @ bs=4
    eng2 = ff.serve(slots=2, max_new_tokens=5, prefill_chunk=4,
                    kv_layout="paged", kv_block_size=4)
    same = [list(shared8), list(shared8)]
    out2 = eng2.generate(same)
    assert out2[0] == out2[1]
    st2 = eng2.block_manager.stats
    assert st2.shared_tokens >= len(shared8) - 1
    assert st2.cow_copies >= 1
    contig2 = ff.serve(slots=2, max_new_tokens=5, prefill_chunk=4,
                       kv_layout="contiguous")
    assert out2 == contig2.generate(same)


def test_paged_refcount_exact_reclamation():
    """Eviction returns exactly the blocks a request held: refcounts hit
    zero in step with completions, shared blocks survive until the LAST
    holder leaves, and the pool drains to empty."""
    from flexflow_tpu.serving.paged import BlockManager

    # pure host-side unit check first (no mesh): see serving/paged.py
    bm = BlockManager(num_blocks=16, block_size=4, table_width=4)
    P1 = list(range(8))
    assert bm.reserve(101, len(P1), 4)
    bm.bind_reservation(101, 0)
    assert bm.admit(0, P1) == 0
    bm.ensure_writable(0, range(8))
    bm.register_prompt(0, P1)
    assert bm.reserve(102, len(P1) + 1, 4)
    bm.bind_reservation(102, 1)
    assert bm.admit(1, P1 + [50]) == 8
    held = bm.blocks_in_use
    bm.release(0)  # shared blocks must survive slot 0's exit
    assert bm.blocks_in_use == held - 0  # slot 0 held only shared blocks
    assert all(bm.refcount(b) == 1 for b in bm._tables[1])
    bm.release(1)
    assert bm.blocks_in_use == 0 and bm.free_blocks == 15
    bm.check_invariants()

    # engine-level: a drained engine's pool is empty, and a second wave
    # reuses the reclaimed blocks without growth
    ff = _build_lm(batch=1)
    eng = ff.serve(slots=2, max_new_tokens=4, prefill_chunk=4,
                   kv_layout="paged", kv_block_size=4)
    eng.generate(PROMPTS)
    mgr = eng.block_manager
    assert mgr.blocks_in_use == 0
    peak1 = mgr.stats.blocks_in_use_peak
    eng.generate(PROMPTS)
    assert mgr.blocks_in_use == 0
    assert mgr.stats.blocks_in_use_peak == peak1, \
        "a second identical wave must not grow the working set"
    mgr.check_invariants()


def test_chunked_prefill_interleaves_with_decode():
    """A long prompt's prefill is spread one chunk per iteration, and the
    in-flight decode advances BETWEEN those chunks — without changing its
    token stream (both layouts)."""
    for layout in ("paged", "contiguous"):
        ff = _build_lm(batch=1)
        eng = ff.serve(slots=2, max_new_tokens=10, prefill_chunk=4,
                       kv_layout=layout)
        short = eng.submit(PROMPTS[0])
        # drive until the short request is decoding
        for _ in range(3):
            eng.step()
        s_short = next(s for s in eng.scheduler.slots
                       if s.request is short)
        assert s_short.decoding
        gen_before = len(short.generated)
        long_req = eng.submit(list(range(1, 17)))  # 16 tokens = 4 chunks
        progressed = []
        while long_req.first_token_t is None:
            eng.step()
            progressed.append(len(short.generated))
        # the decode moved during the long prefill, one token per
        # iteration — chunked prefill never stalled the batch
        assert progressed[0] > gen_before
        assert len(progressed) >= 4, "16-token prompt needs >= 4 chunks"
        eng.run_until_drained()

        solo = ff.serve(slots=2, max_new_tokens=10, prefill_chunk=4,
                        kv_layout=layout)
        assert solo.generate([PROMPTS[0]])[0] == short.generated
        assert solo.generate([list(range(1, 17))])[0] == long_req.generated


def test_paged_scratch_block_guard():
    """The reserved scratch block is the paged equivalent of the
    contiguous scratch ROW (regression for the NaN-poisoning guard):
    position-clipped writes land zeros in block 0 and disturb no live
    block, even when the incoming K/V rows are NaN."""
    import jax.numpy as jnp

    from flexflow_tpu.ops.base import OpContext, get_op_def
    from flexflow_tpu.fftype import OperatorType as OT
    from flexflow_tpu.ops import PagedIncMultiHeadAttentionParams

    E, H, bs, nb, max_seq = 8, 2, 4, 5, 16
    p = PagedIncMultiHeadAttentionParams(E, H, max_seq, bs, nb,
                                         use_bias=False, impl="xla")
    rs = np.random.RandomState(0)
    weights = {w: jnp.asarray(rs.randn(E, E), jnp.float32)
               for w in ("wq", "wk", "wv", "wo")}
    pool_k = jnp.asarray(rs.randn(nb, bs, E), jnp.float32)
    pool_v = jnp.asarray(rs.randn(nb, bs, E), jnp.float32)
    weights["pool_k"], weights["pool_v"] = pool_k, pool_v
    # slot 0 writes position 5 (live, block 1 of its table -> phys 2);
    # slot 1 is clipped to scratch AND carries NaN hidden state (the
    # OOB-position-embedding case the contiguous guard exists for)
    x = jnp.asarray(rs.randn(2, 1, E), jnp.float32)
    x = x.at[1].set(jnp.nan)
    positions = jnp.asarray([[5], [max_seq]], jnp.int32)
    table = jnp.asarray([[1, 2, 3, 4], [0, 0, 0, 0]], jnp.int32)
    fwd = get_op_def(OT.OP_PAGED_INC_MULTIHEAD_ATTENTION).forward
    outs, state = fwd(p, [x, positions, table], weights, None,
                      OpContext(training=False))
    new_k = state["pool_k"]
    # live write: block 2 row 1 (pos 5 = block 1, offset 1) changed
    assert not np.allclose(np.asarray(new_k[2, 1]),
                           np.asarray(pool_k[2, 1]))
    # every OTHER row of every non-scratch block is untouched
    mask = np.ones((nb, bs), bool)
    mask[2, 1] = False
    mask[0, :] = False
    np.testing.assert_array_equal(
        np.asarray(new_k)[mask], np.asarray(pool_k)[mask])
    # the scratch block took the clipped write — as ZEROS, never NaN
    assert np.isfinite(np.asarray(new_k[0])).all()
    assert np.isfinite(np.asarray(state["pool_v"][0])).all()
    # clipped position max_seq-1 = 15 → scratch row 15 % bs = 3
    np.testing.assert_array_equal(
        np.asarray(new_k[0, (max_seq - 1) % bs]), np.zeros((E,)))
    # slot 0's output is finite (slot 1's NaN never crossed rows)
    assert np.isfinite(np.asarray(outs[0][0])).all()


def test_paged_warmstart_layout_fingerprint(tmp_path):
    """--serve-kv-layout round-trips through the warm-start fingerprint:
    each layout's second compile is a cache hit, and the two layouts
    NEVER share a plan address (a paged compile after a contiguous one
    still searches)."""
    ws = str(tmp_path / "ws")
    ff = _build_lm(mesh=(2, 4, 1, 1), batch=8,
                   argv=["--only-data-parallel"])
    ov = dict(only_data_parallel=False, search_budget=4,
              enable_parameter_parallel=True,
              enable_attribute_parallel=True, warmstart_dir=ws)
    kw = dict(slots=8, max_new_tokens=4, prefill_chunk=4,
              config_overrides=ov)

    paged1 = ff.serve(kv_layout="paged", **kw)
    assert paged1.decode_model._plan_source == "search"
    out1 = paged1.generate(PROMPTS[:2])

    # the contiguous compile must MISS the paged entry (fresh search) ...
    with _SearchSpy() as spy:
        contig1 = ff.serve(kv_layout="contiguous", **kw)
    assert contig1.decode_model._plan_source == "search"
    assert spy.searches == 1
    assert contig1.generate(PROMPTS[:2]) == out1

    # ... while each layout's OWN second compile is a zero-eval hit
    with _SearchSpy() as spy:
        paged2 = ff.serve(kv_layout="paged", **kw)
        contig2 = ff.serve(kv_layout="contiguous", **kw)
    assert spy.searches == 0 and spy.evals == 0
    assert paged2.decode_model._plan_source == "cache"
    assert contig2.decode_model._plan_source == "cache"
    assert paged2.generate(PROMPTS[:2]) == out1


def test_paged_pool_exhaustion_blocks_admission():
    """A pool too small for two resident requests head-blocks admission
    (FCFS) instead of failing mid-decode: the second request waits for
    the first to release its blocks, and completions stay correct."""
    ff = _build_lm(batch=1)
    # 4 blocks + scratch: one request (prompt 5 + 3 new = 2 blocks @ bs=4
    # + COW slack) fits, two do not
    eng = ff.serve(slots=2, max_new_tokens=3, prefill_chunk=4,
                   kv_layout="paged", kv_block_size=4, kv_num_blocks=5)
    r1 = eng.submit(PROMPTS[0])
    r2 = eng.submit(PROMPTS[2])
    eng.step()
    assert eng.scheduler.queue_depth == 1, \
        "pool pressure must keep the second request queued"
    eng.run_until_drained()
    assert r1.finished and r2.finished
    solo = ff.serve(slots=2, max_new_tokens=3, prefill_chunk=4,
                    kv_layout="contiguous")
    assert [r1.generated, r2.generated] == solo.generate(
        [PROMPTS[0], PROMPTS[2]])


def test_paged_analysis_coverage():
    """ffcheck follow-through (ISSUE 11 satellite): the memory-liveness
    pass accounts the pool ONCE per layer (not per slot), the donation
    registry covers the COW copy executable, and the ffsan dtype lattice
    knows the paged op."""
    from flexflow_tpu.analysis import donation, memory
    from flexflow_tpu.analysis.lint import DONATED_CALLEES
    from flexflow_tpu.analysis.numerics import F32_INTERNAL
    from flexflow_tpu.fftype import OperatorType as OT
    from flexflow_tpu.serving import ServingSpec, build_decode_model

    assert OT.OP_PAGED_INC_MULTIHEAD_ATTENTION in F32_INTERNAL
    assert DONATED_CALLEES["_copy_fn"] == (0,)
    table = donation.executor_donation_table()
    assert table["build_block_copy"] == (0,)
    assert not donation.registry_problems()

    ff = _build_lm(batch=1)
    c = _lm_config()
    bs = 8
    dec4, _ = build_decode_model(ff, ServingSpec(
        slots=4, kv_layout="paged", kv_block_size=bs, kv_num_blocks=9))
    dec8, _ = build_decode_model(ff, ServingSpec(
        slots=8, kv_layout="paged", kv_block_size=bs, kv_num_blocks=9))
    m4 = memory.analyze(dec4.graph, dec4.mesh, training=False)
    m8 = memory.analyze(dec8.graph, dec8.mesh, training=False)
    pool_bytes = c.num_layers * 2 * 9 * bs * c.hidden_size * 4
    # doubling SLOTS must not change the pool's share of weight bytes —
    # the pool is per layer, not per slot (the contiguous cache, by
    # contrast, doubles)
    assert m8["weight_bytes"] == m4["weight_bytes"]
    # and the pool is actually in there: shrinking the pool to the
    # 2-block minimum removes exactly the missing blocks' bytes
    dec_min, _ = build_decode_model(ff, ServingSpec(
        slots=4, kv_layout="paged", kv_block_size=bs, kv_num_blocks=2))
    m_min = memory.analyze(dec_min.graph, dec_min.mesh, training=False)
    assert m4["weight_bytes"] - m_min["weight_bytes"] == \
        pool_bytes - c.num_layers * 2 * 2 * bs * c.hidden_size * 4


def test_flash_decode_kernel_matches_reference():
    """The Pallas single-query decode kernel (interpret mode on CPU)
    matches the einsum reference across partial/full/one-token cache
    fills. Converted to a clean skip by the conftest capability probe
    when the environment lacks the Pallas APIs."""
    import jax.numpy as jnp

    from flexflow_tpu.kernels.flash_attention import (
        decode_attention_reference,
        flash_decode_attention,
    )

    rs = np.random.RandomState(0)
    slots, S, H, hd = 3, 256, 2, 64
    E = H * hd
    q = jnp.asarray(rs.randn(slots, 1, E), jnp.float32)
    k = jnp.asarray(rs.randn(slots, S, E), jnp.float32)
    v = jnp.asarray(rs.randn(slots, S, E), jnp.float32)
    lengths = jnp.asarray([1, 100, 256], jnp.int32)
    ref = decode_attention_reference(q, k, v, (lengths - 1)[:, None],
                                     num_heads=H)
    out = flash_decode_attention(q, k, v, lengths, num_heads=H,
                                 block_k=128, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_paged_flash_decode_kernel_matches_reference():
    """The PAGED Pallas decode kernel — kv grid walking the page table
    via scalar prefetch — matches the gather + einsum oracle across
    partial/full/one-token fills, scrambled tables, and blocks shared
    between slots. Converted to a clean skip by the conftest capability
    probe when the environment lacks the Pallas APIs."""
    import jax.numpy as jnp

    from flexflow_tpu.kernels.flash_attention import (
        paged_decode_attention_reference,
        paged_flash_decode_attention,
    )

    rs = np.random.RandomState(0)
    slots, H, hd, bs = 3, 2, 64, 16
    E = H * hd
    W = 16  # 16 blocks x 16 rows = 256 logical rows
    nb = 2 * W + 2
    pool_k = jnp.asarray(rs.randn(nb, bs, E), jnp.float32)
    pool_v = jnp.asarray(rs.randn(nb, bs, E), jnp.float32)
    table = np.zeros((slots, W), np.int32)
    table[0] = rs.permutation(np.arange(1, W + 1))
    table[1] = rs.permutation(np.arange(W + 1, 2 * W + 1))
    table[2] = table[0]  # slot 2 SHARES slot 0's blocks (prefix reuse)
    table = jnp.asarray(table)
    q = jnp.asarray(rs.randn(slots, 1, E), jnp.float32)
    lengths = jnp.asarray([1, 100, 256], jnp.int32)
    ref = paged_decode_attention_reference(
        q, pool_k, pool_v, table, (lengths - 1)[:, None], num_heads=H)
    out = paged_flash_decode_attention(
        q, pool_k, pool_v, table, lengths, num_heads=H, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
