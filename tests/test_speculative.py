"""Speculative-decoding tests (serving/speculative.py, docs/serving.md).

The acceptance surface of the drafter/verify path on the 8-device CPU
mesh:

  - speculative token streams are BIT-IDENTICAL to the unified engine at
    both acceptance extremes — a drafter that always agrees (seed-clone
    of the target) and one that never does (monkeypatched proposals of a
    token the target never samples);
  - slot reuse under continuous batching never leaks drafter cursor
    state between residents;
  - verify rollback composes with the paged COW/radix machinery — the
    BlockManager invariants hold after every speculative round and a
    shared prefix is never poisoned by rejected rows;
  - the drafter compiles role-keyed: a second speculative engine against
    one --warmstart-dir is a 0-eval plan-cache hit for BOTH plans;
  - the acceptance EMA round-trips through the warm-start calibration
    DB keyed per (target, drafter) pair;
  - payoff decisions carry every factor and reproduce arithmetically
    under the doctor's rule, and the flag validation names the flag.
"""

import sys

import pytest

from test_serving import _SearchSpy

PROMPTS = [[3, 7, 11, 2, 5], [5, 2], [1, 9, 30, 30, 12, 4, 8], [60, 1, 2]]


def _lm_config(**kw):
    from flexflow_tpu.models import TransformerLMConfig

    base = dict(vocab_size=64, hidden_size=32, num_heads=4, num_layers=2,
                sequence_length=32, attention_impl="xla")
    base.update(kw)
    return TransformerLMConfig(**base)


def _build_lm(mesh=(1, 1, 1, 1), batch=1, argv=(), **lm_kw):
    sys.argv = ["test"] + list(argv)
    from flexflow_tpu import FFConfig, FFModel, LossType, SGDOptimizer
    from flexflow_tpu.models import build_transformer_lm

    cfg = FFConfig()
    if cfg.mesh_axis_sizes is None:
        cfg.mesh_axis_sizes = mesh
    cfg.batch_size = batch
    ff = FFModel(cfg)
    build_transformer_lm(ff, _lm_config(**lm_kw), batch_size=batch)
    ff.compile(optimizer=SGDOptimizer(lr=0.01),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff


def _force_speculation(eng):
    """White-box: bypass the payoff gate so every eligible round
    speculates — the sustained-speculation harness the rollback/reuse
    tests need. The honest gate (correctly) declines on CPU, where a
    drafter call costs as much as a target call, and an all-reject EMA
    zeroes the expected payoff entirely."""
    def always(k_cap):
        d = {"k": min(eng.k_max, k_cap),
             "reason": "bootstrap",
             "chosen": "speculate" if k_cap >= 1 else "decode",
             "would_speculate": k_cap >= 1,
             "acceptance_ema": float(eng.acceptance_ema),
             "acceptance_samples": int(eng.acceptance_samples)}
        eng._decision_counts[d["chosen"]] += 1
        eng.decisions.append(d)
        return d

    eng._decide = always


def _reject_all(eng, tok):
    """Monkeypatch the drafter to always propose `tok` — with `tok`
    verified absent from every plain-decode stream, every proposal
    rejects and every verify emits exactly the correction token."""
    def propose(decoding, ks):
        return ({i: [tok] * k for i, k in ks.items()}, 1e-6)

    eng.drafter.propose = propose


# ------------------------------------------------------------ bit-identity


def test_spec_all_accept_bit_identity():
    """Drafter = seed-clone of the target: every proposal matches, the
    stream is bit-identical, and the engine's speculation accounting
    shows the all-accept extreme (acceptance rate 1.0, K+1 tokens per
    verified slot-round)."""
    ff = _build_lm()
    plain = ff.serve(slots=2, max_new_tokens=8, prefill_chunk=4)
    base = plain.generate(PROMPTS)

    dff = _build_lm()  # same config + seed -> identical weights
    eng = ff.serve(speculate=True, draft_model=dff, slots=2,
                   max_new_tokens=8, prefill_chunk=4)
    assert eng.generate(PROMPTS) == base
    sp = eng.stats()["speculation"]
    assert sp["rounds"] >= 1, "bootstrap round must have speculated"
    assert sp["draft_tokens"] > 0
    assert sp["accepted_tokens"] == sp["draft_tokens"]
    assert sp["acceptance_rate"] == 1.0
    assert eng.acceptance_ema == 1.0
    # metrics plane: the pre-created spec series saw the rounds
    assert eng._c_spec_rounds.value == sp["rounds"]
    assert eng._h_spec_accept_rate.count > 0


def test_spec_all_reject_bit_identity():
    """Adversarial drafter (proposes a token the target never samples):
    every round rejects everything and emits only the correction token —
    still bit-identical, and the acceptance EMA collapses toward 0."""
    ff = _build_lm()
    plain = ff.serve(slots=2, max_new_tokens=8, prefill_chunk=4)
    base = plain.generate(PROMPTS)
    bad = 63
    assert all(bad not in g for g in base), \
        "pick a proposal token plain decode never emits"

    dff = _build_lm()
    eng = ff.serve(speculate=True, draft_model=dff, slots=2,
                   max_new_tokens=8, prefill_chunk=4)
    _force_speculation(eng)
    _reject_all(eng, bad)
    assert eng.generate(PROMPTS) == base
    sp = eng.stats()["speculation"]
    assert sp["rounds"] > 1, "forced speculation must have run repeatedly"
    assert sp["accepted_tokens"] == 0
    # every rejected round emits exactly one correction token per slot
    assert sp["rounds"] <= sp["emitted_tokens"] <= 2 * sp["rounds"]
    assert eng.acceptance_ema < 0.5


def test_spec_slot_reuse_under_continuous_batching():
    """Six requests through two slots with sustained speculation: every
    admission reuses a slot whose drafter cursor belonged to the prior
    resident — the owner check must reset it, keeping streams identical
    to the unified engine's interleaved run."""
    ff = _build_lm()
    prompts = PROMPTS + [[2, 4, 6, 8], [33, 1]]
    plain = ff.serve(slots=2, max_new_tokens=6, prefill_chunk=4)
    base = plain.generate(prompts)

    dff = _build_lm()
    eng = ff.serve(speculate=True, draft_model=dff, slots=2,
                   max_new_tokens=6, prefill_chunk=4)
    _force_speculation(eng)
    assert eng.generate(prompts) == base
    assert eng.stats()["speculation"]["rounds"] > 1
    assert eng.scheduler.drained


def test_spec_paged_cow_radix_rollback_safety():
    """Rejection-heavy speculation over shared-prefix prompts on the
    paged layout: the verify rollback (host cursor rewind) must never
    corrupt a shared block — BlockManager invariants hold after every
    step, streams stay bit-identical, and a SECOND pass over the same
    prompts (radix cross-time hits serving cached prefix blocks) still
    matches."""
    shared = [7, 7, 7, 7, 3, 3, 3, 3]
    prompts = [shared + [t] for t in (1, 2, 3)]
    ff = _build_lm()
    kw = dict(slots=2, max_new_tokens=6, prefill_chunk=4,
              kv_block_size=4, kv_num_blocks=64)
    plain = ff.serve(**kw)
    base = plain.generate(prompts)
    bad = 63
    assert all(bad not in g for g in base)

    dff = _build_lm()
    eng = ff.serve(speculate=True, draft_model=dff, **kw)
    assert eng.block_manager is not None
    _force_speculation(eng)
    _reject_all(eng, bad)
    for ever in range(2):  # second pass: cross-time radix hits
        reqs = [eng.submit(p, max_new_tokens=6) for p in prompts]
        while not eng.scheduler.drained:
            eng.step()
            eng.block_manager.check_invariants()
        assert [r.generated for r in reqs] == base, f"pass {ever}"
    assert eng.block_manager.stats.cross_time_hits > 0, \
        "second pass never hit the radix cache — test is vacuous"
    assert eng.stats()["speculation"]["rounds"] > 1


# ------------------------------------------------------------ placement


def test_spec_draft_chips_disjoint_submesh():
    """--serve-draft-chips carves the drafter onto the trailing chips:
    device sets are disjoint, the section records the split, and the
    stream stays bit-identical to the colocated plain engine."""
    ff = _build_lm(mesh=(8, 1, 1, 1), batch=8)
    plain = ff.serve(slots=4, max_new_tokens=6, prefill_chunk=4)
    base = plain.generate(PROMPTS)

    dff = _build_lm(mesh=(1, 1, 1, 1), batch=1)
    eng = ff.serve(speculate=True, draft_model=dff, draft_chips=4,
                   slots=4, max_new_tokens=6, prefill_chunk=4)
    tdev = {d.id for d in eng.decode_model.mesh.devices.flat}
    ddev = {d.id for d in
            eng.drafter.engine.decode_model.mesh.devices.flat}
    assert len(tdev) == 4 and len(ddev) == 4
    assert not tdev & ddev, "drafter and target sub-meshes overlap"
    assert eng.generate(PROMPTS) == base
    sec = eng.speculation_section()
    assert sec["draft_chips"] == 4 and not sec["colocated"]
    assert eng.drafter.engine.decode_model.config.serve_role == "draft"


# ------------------------------------------------------------ warm start


def test_spec_warmstart_role_keyed_plan_cache(tmp_path):
    """Second speculative engine against one --warmstart-dir: ZERO
    search evaluations — the target hits the plain serving plan address
    (colocated speculation adds no config delta) and the drafter hits
    its role="draft"-keyed address."""
    ws = str(tmp_path / "ws")
    search_argv = ["--warmstart-dir", ws, "--search-budget", "4",
                   "--enable-parameter-parallel",
                   "--enable-attribute-parallel"]
    ff = _build_lm(mesh=(2, 4, 1, 1), batch=8, argv=search_argv)
    # the drafter's decode config derives from the DRAFT model's own
    # config (user overrides apply to the target only), so its search
    # and warm-start flags ride the draft model's argv
    dff = _build_lm(mesh=(2, 4, 1, 1), batch=8, argv=search_argv)
    kw = dict(slots=8, max_new_tokens=4, prefill_chunk=4)
    eng1 = ff.serve(speculate=True, draft_model=dff, **kw)
    assert eng1.decode_model._plan_source == "search"
    assert eng1.drafter.engine.decode_model._plan_source == "search"
    out1 = eng1.generate(PROMPTS[:2])

    with _SearchSpy() as spy:
        eng2 = ff.serve(speculate=True, draft_model=dff, **kw)
    assert spy.searches == 0, "speculative re-serve must not re-search"
    assert spy.evals == 0, "speculative re-serve must cost 0 evaluations"
    assert eng2.decode_model._plan_source == "cache"
    assert eng2.drafter.engine.decode_model._plan_source == "cache"
    assert eng2.generate(PROMPTS[:2]) == out1


def test_spec_acceptance_ema_roundtrips_calibration_db(tmp_path):
    """The per-(target, drafter) acceptance EMA persists in the
    warm-start calibration DB at drain and seeds a FRESH process's
    engine (new model objects, same arch + dir) — the r20
    migration-fidelity treatment."""
    from flexflow_tpu.serving.speculative import (
        DEFAULT_ACCEPTANCE, load_acceptance,
    )

    ws = str(tmp_path / "ws")
    ff = _build_lm(argv=["--warmstart-dir", ws])
    dff = _build_lm(argv=["--warmstart-dir", ws])
    eng = ff.serve(speculate=True, draft_model=dff, slots=2,
                   max_new_tokens=8, prefill_chunk=4)
    eng.generate(PROMPTS)  # drain -> forced persist
    assert eng.acceptance_samples > 0
    assert eng.acceptance_ema != DEFAULT_ACCEPTANCE

    ff2 = _build_lm(argv=["--warmstart-dir", ws])
    dff2 = _build_lm(argv=["--warmstart-dir", ws])
    eng2 = ff2.serve(speculate=True, draft_model=dff2, slots=2,
                     max_new_tokens=8, prefill_chunk=4)
    assert eng2.pair_key == eng.pair_key
    assert eng2.acceptance_ema == pytest.approx(eng.acceptance_ema)
    assert eng2.acceptance_samples == eng.acceptance_samples
    # and the loader itself reports the DB entry, not the default
    rate, samples = load_acceptance(ff2, eng.pair_key)
    assert rate == pytest.approx(eng.acceptance_ema) and samples > 0


# ------------------------------------------------------------ payoff gate


def test_spec_payoff_decision_arithmetic():
    """The decision record reproduces under the doctor's rule: lhs =
    K·draft + verify, rhs = (Σ a^i)·decode with the engine's own
    accumulation order, chosen agrees with the inequality, and the
    engine picks the net-maximizing K."""
    from flexflow_tpu.search.cost_model import price_verify_scale
    from flexflow_tpu.serving.speculative import expected_accepted

    assert expected_accepted(0.8, 3) == pytest.approx(
        0.8 + 0.8 ** 2 + 0.8 ** 3)
    assert price_verify_scale(1) == 1.0
    assert price_verify_scale(5) == pytest.approx(2.0)

    ff = _build_lm()
    dff = _build_lm()
    eng = ff.serve(speculate=True, draft_model=dff, slots=2,
                   max_new_tokens=4, prefill_chunk=4)
    eng._decode_cost_s = 1.0
    eng._draft_cost_s = 0.1
    eng._verify_cost_s = {k + 1: 0.2 + 0.05 * k for k in range(1, 5)}
    eng.acceptance_ema, eng.acceptance_samples = 0.8, 10
    d = eng._decide(4)
    assert d["reason"] == "payoff"
    # doctor-rule reproduction, same accumulation order
    exp, x = 0.0, 1.0
    for _ in range(d["k"]):
        x *= d["acceptance_ema"]
        exp += x
    lhs = d["k"] * d["draft_cost_s"] + d["verify_cost_s"]
    rhs = exp * d["decode_cost_s"]
    assert d["expected_accepted"] == pytest.approx(exp, abs=1e-12)
    assert d["lhs_s"] == pytest.approx(lhs, abs=1e-12)
    assert d["rhs_s"] == pytest.approx(rhs, abs=1e-12)
    assert d["would_speculate"] == (lhs < rhs)
    assert d["chosen"] == ("speculate" if lhs < rhs else "decode")
    # K maximizes net over every candidate
    nets = []
    for k in range(1, 5):
        e, x = 0.0, 1.0
        for _ in range(k):
            x *= 0.8
            e += x
        nets.append(e * 1.0 - (k * 0.1 + eng._verify_cost_s[k + 1]))
    assert d["k"] == nets.index(max(nets)) + 1
    # no headroom forces plain decode with the reason on record
    d0 = eng._decide(0)
    assert d0["reason"] == "no_headroom" and d0["chosen"] == "decode"
    # an unmeasured verify bucket prices off the cost-model prior and
    # says so
    eng._verify_cost_s = {}
    d2 = eng._decide(2)
    assert d2["verify_cost_source"] == "assumed"
    assert eng.decisions[-1] is d2


# ------------------------------------------------------------ validation


def test_spec_flag_and_argument_validation():
    """Misconfigurations fail fast with the flag named: chip budgets
    past the visible device count, speculate without a drafter,
    speculate+disaggregate, K < 1, a drafter whose positional table is
    too short, and a drafter with a foreign vocabulary."""
    import jax

    n = len(jax.devices())
    ff = _build_lm(argv=["--serve-draft-chips", str(n)])
    with pytest.raises(ValueError, match="--serve-draft-chips"):
        ff.serve(slots=2)
    ff = _build_lm(argv=["--serve-prefill-chips", str(n + 1)])
    with pytest.raises(ValueError, match="--serve-prefill-chips"):
        ff.serve(slots=2)

    ff = _build_lm()
    with pytest.raises(ValueError, match="draft_model"):
        ff.serve(speculate=True, slots=2)
    with pytest.raises(ValueError, match="mutually exclusive"):
        ff.serve(speculate=True, disaggregate=True, draft_model=ff,
                 slots=2)
    with pytest.raises(ValueError, match="--serve-spec-k"):
        ff.serve(speculate=True, draft_model=ff, spec_k=0, slots=2)
    # kwarg draft_chips out of range names the flag too
    with pytest.raises(ValueError, match="--serve-draft-chips"):
        ff.serve(speculate=True, draft_model=ff, draft_chips=n, slots=2)

    short = _build_lm(sequence_length=16)
    with pytest.raises(ValueError, match="positional table"):
        ff.serve(speculate=True, draft_model=short, slots=2)
    alien = _build_lm(vocab_size=32)
    with pytest.raises(ValueError, match="vocab"):
        ff.serve(speculate=True, draft_model=alien, slots=2)
