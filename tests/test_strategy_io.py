"""Strategy import/export tests (--export-strategy / --import-strategy,
reference model.cc:3599-3608 — where the import path was vestigial; here a
searched plan round-trips and replays without re-searching), plus the
--machine-model-file loader."""

import json
import sys

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P


def test_strategy_json_round_trip():
    from flexflow_tpu.parallel.strategies import Strategy

    s = Strategy()
    s.set_output("fc1", 0, (("data",), (), ("model",)))
    s.set_output("fc1", 1, ((), ("data", "model")))
    s.set_weight("fc1", "kernel", P(None, "model"))
    s.set_weight("fc1", "bias", P("model"))
    s.set_weight("attn", "wo", P(("data", "model"), None))

    s2 = Strategy.from_json(json.loads(json.dumps(s.to_json())))
    assert s2.overrides["fc1"]["outputs"][0] == (("data",), (), ("model",))
    assert s2.overrides["fc1"]["outputs"][1] == ((), ("data", "model"))
    assert s2.overrides["fc1"]["weights"]["kernel"] == P(None, "model")
    assert s2.overrides["fc1"]["weights"]["bias"] == P("model")
    assert s2.overrides["attn"]["weights"]["wo"] == P(("data", "model"), None)


def test_strategy_file_version_check(tmp_path):
    from flexflow_tpu.parallel.strategies import Strategy

    p = tmp_path / "bad.json"
    p.write_text(json.dumps({"version": 99, "nodes": {}}))
    with pytest.raises(ValueError, match="version"):
        Strategy.load(str(p))


def _build_and_compile(argv, batch=32):
    sys.argv = ["test"] + list(argv)
    from flexflow_tpu import (
        ActiMode, FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
    )

    config = FFConfig()
    config.batch_size = batch
    ff = FFModel(config)
    x = ff.create_tensor((batch, 64))
    t = ff.dense(x, 256, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 256, ActiMode.AC_MODE_RELU, name="fc2")
    t = ff.dense(t, 10, name="head")
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])
    return ff


def test_export_then_import_replays_without_search(tmp_path):
    """Search once with --export-strategy; the second compile imports the
    plan and must (a) skip the search and (b) end with the same specs."""
    plan = str(tmp_path / "plan.json")
    ff1 = _build_and_compile(
        ["--mesh", "2,4,1,1", "--budget", "6",
         "--enable-parameter-parallel", "--export-strategy", plan])
    exported = json.load(open(plan))
    assert exported["version"] == 1

    # importing must bypass the search entirely
    import flexflow_tpu.search.joint as joint

    called = {"n": 0}
    orig = joint.joint_graph_optimize

    def spy(*a, **kw):
        called["n"] += 1
        return orig(*a, **kw)

    joint.joint_graph_optimize = spy
    try:
        ff2 = _build_and_compile(
            ["--mesh", "2,4,1,1", "--budget", "6",
             "--enable-parameter-parallel", "--import-strategy", plan])
    finally:
        joint.joint_graph_optimize = orig
    assert called["n"] == 0, "import-strategy must not re-search"

    # the replayed model carries the same per-node weight specs
    for node in ff2.graph.topo_order():
        ov = exported["nodes"].get(node.name)
        if not ov:
            continue
        for wname, entries in ov["weights"].items():
            got = node.weight_axes.get(wname)
            assert got is not None, (node.name, wname)
            want = tuple(tuple(e) if isinstance(e, list) else e
                         for e in entries)
            assert tuple(got) == want, (node.name, wname, got, want)

    # and still trains
    rs = np.random.RandomState(0)
    c = rs.randn(10, 64) * 3
    y = rs.randint(0, 10, 512)
    xs = (c[y] + rs.randn(512, 64)).astype(np.float32)
    ff2.fit(xs, y.reshape(-1, 1).astype(np.int32), epochs=2)
    assert ff2.get_perf_metrics().get_accuracy() >= 0.8


def test_machine_model_file(tmp_path):
    from flexflow_tpu.machine import build_mesh, MeshShape
    from flexflow_tpu.search.machine_model import (
        CHIPS, machine_model_from_file,
    )

    mesh = build_mesh(MeshShape((2, 4, 1, 1)))
    p = tmp_path / "mm.json"
    p.write_text(json.dumps({
        "chip": {"name": "v5p", "ici_bandwidth": 2e10},
        "axis_links": {"model": 2},
        "dcn_axes": ["data"],
    }))
    m = machine_model_from_file(str(p), mesh)
    assert m.chip.peak_flops == CHIPS["v5p"].peak_flops
    assert m.chip.ici_bandwidth == 2e10
    assert m.axis_links["model"] == 2
    assert "data" in m.axis_over_dcn
    # DCN axis must be priced slower than the doubled-ICI axis
    assert m.all_reduce(1e9, "data") > m.all_reduce(1e9, "model")

    p2 = tmp_path / "mm2.json"
    p2.write_text(json.dumps({"chip": "nope"}))
    with pytest.raises(ValueError, match="unknown chip"):
        machine_model_from_file(str(p2), mesh)


def test_parity_only_flags_warn(capsys):
    sys.argv = ["test", "--segment-size", "1024"]
    from flexflow_tpu import FFConfig

    FFConfig()
    err = capsys.readouterr().err
    assert "no effect" in err


def test_machine_model_congestion(tmp_path):
    """Per-axis congestion derating (EnhancedMachineModel analog)."""
    from flexflow_tpu.machine import build_mesh, MeshShape
    from flexflow_tpu.search.machine_model import machine_model_from_file

    mesh = build_mesh(MeshShape((2, 4, 1, 1)))
    p = tmp_path / "mm.json"
    p.write_text(json.dumps({"chip": "v5p",
                             "congestion": {"model": 2.0}}))
    m = machine_model_from_file(str(p), mesh)
    p2 = tmp_path / "mm2.json"
    p2.write_text(json.dumps({"chip": "v5p"}))
    m2 = machine_model_from_file(str(p2), mesh)
    # congested axis prices 2x the bytes-proportional part
    free = m2.all_reduce(1e9, "model")
    congested = m.all_reduce(1e9, "model")
    assert congested > 1.8 * free
    assert m.all_reduce(1e9, "data") == m2.all_reduce(1e9, "data")


def test_machine_model_rejects_fractional_congestion(tmp_path):
    from flexflow_tpu.machine import build_mesh, MeshShape
    from flexflow_tpu.search.machine_model import machine_model_from_file

    mesh = build_mesh(MeshShape((2, 4, 1, 1)))
    p = tmp_path / "mm.json"
    p.write_text(json.dumps({"chip": "v5p",
                             "congestion": {"model": 0.5}}))
    with pytest.raises(ValueError, match="congestion"):
        machine_model_from_file(str(p), mesh)


def test_machine_model_rejects_unknown_congestion_axis(tmp_path):
    from flexflow_tpu.machine import build_mesh, MeshShape
    from flexflow_tpu.search.machine_model import machine_model_from_file

    mesh = build_mesh(MeshShape((2, 4, 1, 1)))
    p = tmp_path / "mm.json"
    p.write_text(json.dumps({"chip": "v5p",
                             "congestion": {"mdoel": 4.0}}))  # typo
    with pytest.raises(ValueError, match="congestion axes"):
        machine_model_from_file(str(p), mesh)
