"""Substitution engine tests (reference: substitution.cc GraphXfer).

Covers: the backtracking matcher, algebraic merge (linear+relu), parallel-op
insertion (replicate_linear_combine, replicate_attention_reduce — the latter
inserts an explicit Reduction node the config-only search cannot express),
base_optimize best-first search, JSON rule loading, and end-to-end numerics
of rewritten graphs against the unrewritten baseline.
"""

import json

import numpy as np
import pytest

from flexflow_tpu import (
    ActiMode,
    FFConfig,
    FFModel,
    LossType,
    SGDOptimizer,
)
from flexflow_tpu.fftype import DataType, OperatorType as OT


def _mk_config(argv=()):
    import sys

    old = sys.argv
    sys.argv = ["t", *argv]
    try:
        return FFConfig()
    finally:
        sys.argv = old


def _mlp(config, prefix="m"):
    ff = FFModel(config)
    x = ff.create_tensor((config.batch_size, 32), name=f"{prefix}_in")
    t = ff.dense(x, 64, name=f"{prefix}_fc1")
    t = ff.relu(t, name=f"{prefix}_relu")
    t = ff.dense(t, 10, name=f"{prefix}_fc2")
    return ff, x


def _attn_model(config, prefix="a"):
    ff = FFModel(config)
    x = ff.create_tensor((config.batch_size, 16, 32), name=f"{prefix}_in")
    t = ff.multihead_attention(x, x, x, 32, 4, name=f"{prefix}_attn")
    t = ff.dense(t, 10, name=f"{prefix}_head")
    return ff, x


def test_matcher_finds_all_linears():
    from flexflow_tpu.search.substitution import (
        create_partition_linear_combine,
    )

    config = _mk_config(["-b", "8"])
    ff, _ = _mlp(config)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    xfer = create_partition_linear_combine(2, ActiMode.AC_MODE_NONE)
    matches = xfer.find_matches(ff.graph)
    # both dense layers have AC_MODE_NONE activation
    assert len(matches) == 2


def test_linear_relu_merge_numerics():
    from flexflow_tpu.search.substitution import (
        create_linear_relu_merge,
        propagate_parallel_state,
    )

    config = _mk_config(["-b", "8", "--mesh", "1,1,1,1"])
    ff, _ = _mlp(config, prefix="lrm")
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    xfer = create_linear_relu_merge()
    matches = xfer.find_matches(ff.graph)
    assert len(matches) == 1
    ng = xfer.apply(ff.graph, matches[0])
    # relu node folded away
    assert len(ng) == len(ff.graph) - 1
    types = {n.op_type for n in ng.topo_order()}
    assert OT.OP_RELU not in types
    fc1 = next(n for n in ng.topo_order() if n.name == "lrm_fc1")
    assert fc1.params.activation == ActiMode.AC_MODE_RELU


def test_replicate_attention_reduce_inserts_reduction():
    """The flagship rewrite: an explicit Reduction node appears — something
    the config-only UnitySearch cannot express (VERDICT item 3)."""
    from flexflow_tpu.search.substitution import (
        create_replicate_attention_reduce,
    )

    config = _mk_config(["-b", "8", "--mesh", "2,2,1,1"])
    ff, _ = _attn_model(config)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    xfer = create_replicate_attention_reduce(2)
    matches = xfer.find_matches(ff.graph)
    assert len(matches) == 1
    ng = xfer.apply(ff.graph, matches[0])
    types = [n.op_type for n in ng.topo_order()]
    assert OT.OP_REDUCTION in types
    assert OT.OP_REPLICATE in types
    attn = next(n for n in ng.topo_order()
                if n.op_type == OT.OP_MULTIHEAD_ATTENTION)
    # weight shardings implied by the rewrite (column q/k/v, row out-proj)
    assert attn._weight_partition["wq"] == (1, 2)
    assert attn._weight_partition["wo"] == (0, 2)
    # attention output carries the partial-sum replica dim; the Reduction
    # node consumes it
    assert attn.outputs[0].shape.num_replica_dims == 1
    red = next(n for n in ng.topo_order() if n.op_type == OT.OP_REDUCTION)
    assert red.outputs[0].shape.num_replica_dims == 0


def test_rewritten_graph_numerics_match_baseline():
    """Executing the substitution-rewritten model reproduces the baseline
    model's logits (same seed, same layer names → same weights)."""
    rs = np.random.RandomState(0)
    x_np = rs.randn(8, 16, 32).astype(np.float32)

    config_a = _mk_config(["-b", "8", "--mesh", "2,2,1,1"])
    ff_a, _ = _attn_model(config_a)
    ff_a.compile(optimizer=SGDOptimizer(lr=0.1),
                 loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    logits_a, _ = ff_a.executor.build_forward()(
        ff_a._params, ff_a._state, {"a_in": x_np}, False)

    config_b = _mk_config(["-b", "8", "--mesh", "2,2,1,1",
                           "--enable-substitutions", "--budget", "8"])
    ff_b, _ = _attn_model(config_b)
    ff_b.compile(optimizer=SGDOptimizer(lr=0.1),
                 loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    logits_b, _ = ff_b.executor.build_forward()(
        ff_b._params, ff_b._state, {"a_in": x_np}, False)
    np.testing.assert_allclose(np.asarray(logits_a), np.asarray(logits_b),
                               rtol=1e-4, atol=1e-4)


def test_base_optimize_improves_or_keeps_cost():
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.machine_model import machine_model_for_mesh
    from flexflow_tpu.search.substitution import (
        base_optimize,
        evaluate_graph,
        generate_all_pcg_xfers,
    )

    config = _mk_config(["-b", "8", "--mesh", "2,2,1,1"])
    ff, _ = _mlp(config, prefix="bo")
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    cm = CostModel(machine_model_for_mesh(ff.mesh))
    t0, _ = evaluate_graph(ff.graph, ff.mesh, cm)
    xfers = generate_all_pcg_xfers(ff.mesh, config)
    best, cost = base_optimize(ff.graph, ff.mesh, cm, xfers, budget=8)
    assert cost <= t0 * 1.0001


def test_substitution_json_loader(tmp_path):
    from flexflow_tpu.search.substitution import load_rule_collection

    config = _mk_config(["-b", "8", "--mesh", "2,2,1,1"])
    ff, _ = _mlp(config, prefix="jl")
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    rules = {"rules": [
        {"generator": "replicate_linear_combine", "degree": 2,
         "activation": "none"},
        {"generator": "linear_relu_merge"},
    ]}
    p = tmp_path / "rules.json"
    p.write_text(json.dumps(rules))
    xfers = load_rule_collection(str(p), ff.mesh)
    assert len(xfers) == 2
    with pytest.raises(ValueError):
        p2 = tmp_path / "bad.json"
        p2.write_text(json.dumps({"rules": [{"generator": "nope"}]}))
        load_rule_collection(str(p2), ff.mesh)


def test_substitution_json_end_to_end(tmp_path):
    """--substitution-json drives compile through the rewrite search and the
    model still trains (the flag is no longer decorative)."""
    rules = {"rules": [
        {"generator": "replicate_linear_combine", "activation": "none"},
        {"generator": "linear_relu_merge"},
    ]}
    p = tmp_path / "rules.json"
    p.write_text(json.dumps(rules))
    config = _mk_config(["-b", "16", "--mesh", "2,2,1,1",
                         "--substitution-json", str(p), "--budget", "6"])
    ff = FFModel(config)
    x = ff.create_tensor((16, 32), name="sj_in")
    t = ff.dense(x, 64, ActiMode.AC_MODE_RELU, name="sj_fc1")
    t = ff.softmax(ff.dense(t, 8, name="sj_fc2"), name="sj_sm")
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    rs = np.random.RandomState(0)
    c = rs.randn(8, 32) * 3
    y = rs.randint(0, 8, 256)
    xs = (c[y] + rs.randn(256, 32)).astype(np.float32)
    ff.fit(xs, y.reshape(-1, 1).astype(np.int32), epochs=2)
    assert ff.get_perf_metrics().train_all > 0


def test_partition_add_combine_shapes():
    from flexflow_tpu.search.substitution import create_partition_add_combine

    config = _mk_config(["-b", "8", "--mesh", "2,1,1,1"])
    ff = FFModel(config)
    x = ff.create_tensor((8, 32), name="pa_in")
    a = ff.dense(x, 32, name="pa_fc1")
    b = ff.dense(x, 32, name="pa_fc2")
    t = ff.add(a, b, name="pa_add")
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_IDENTITY)
    xfer = create_partition_add_combine(2)
    matches = xfer.find_matches(ff.graph)
    assert len(matches) == 1
    ng = xfer.apply(ff.graph, matches[0])
    add = next(n for n in ng.topo_order() if n.op_type == OT.OP_EW_ADD)
    # batch dim carries the partition degree inside the rewrite region
    assert add.outputs[0].shape.dims[0].degree == 2


def test_partial_sum_through_nonlinear_rejected():
    """A rule composition interposing a nonlinear op between a row-parallel
    producer and its Reduction must be discarded as invalid (ADVICE r2):
    relu(partial sums) != partial(relu)."""
    from flexflow_tpu.parallel.ops import ReductionParams, ReplicateParams
    from flexflow_tpu.pcg.graph import Graph, OpNode
    from flexflow_tpu.search.substitution import propagate_parallel_state
    from flexflow_tpu.tensor import ParallelTensor, ParallelTensorShape

    config = _mk_config(["-b", "8", "--mesh", "2,2,1,1"])
    ff, _ = _attn_model(config, prefix="ps")
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)

    def build(interpose: OT):
        g = Graph()
        inp = OpNode(OT.OP_INPUT, None, name="x")
        inp.outputs = [ParallelTensor(
            ParallelTensorShape.from_shape((8, 16, 32), DataType.DT_FLOAT),
            name="x")]
        g.add_node(inp)
        attn_src = next(n for n in ff.graph.topo_order()
                        if n.op_type == OT.OP_MULTIHEAD_ATTENTION)
        repl = OpNode(OT.OP_REPLICATE, ReplicateParams(2))
        g.add_node(repl)
        g.add_edge(inp, repl, 0, 0)
        attn = OpNode(OT.OP_MULTIHEAD_ATTENTION, attn_src.params,
                      name="attn", initializers=attn_src.initializers)
        attn.weight_specs = list(attn_src.weight_specs)
        g.add_node(attn)
        for i in range(3):
            g.add_edge(repl, attn, 0, i)
        mid = OpNode(interpose, None, name="mid")
        g.add_node(mid)
        g.add_edge(attn, mid, 0, 0)
        red = OpNode(OT.OP_REDUCTION, ReductionParams(2))
        g.add_node(red)
        g.add_edge(mid, red, 0, 0)
        return g

    # nonlinear interposer: invalid candidate, must raise
    with pytest.raises(ValueError, match="nonlinear"):
        propagate_parallel_state(build(OT.OP_RELU))
    # linearity-safe interposer (identity) is fine
    propagate_parallel_state(build(OT.OP_IDENTITY))


def test_reduction_over_pure_replicas_rejected():
    from flexflow_tpu.parallel.ops import ReductionParams, ReplicateParams
    from flexflow_tpu.pcg.graph import Graph, OpNode
    from flexflow_tpu.search.substitution import propagate_parallel_state
    from flexflow_tpu.tensor import ParallelTensor, ParallelTensorShape

    g = Graph()
    inp = OpNode(OT.OP_INPUT, None, name="x")
    inp.outputs = [ParallelTensor(
        ParallelTensorShape.from_shape((8, 32), DataType.DT_FLOAT),
        name="x")]
    g.add_node(inp)
    repl = OpNode(OT.OP_REPLICATE, ReplicateParams(2))
    g.add_node(repl)
    g.add_edge(inp, repl, 0, 0)
    red = OpNode(OT.OP_REDUCTION, ReductionParams(2))
    g.add_node(red)
    g.add_edge(repl, red, 0, 0)
    with pytest.raises(ValueError, match="identical replicas"):
        propagate_parallel_state(g)


def test_logits_marker_survives_softmax_rewrite():
    """partition_softmax_combine moves the logits marker onto the inserted
    Combine; the loss must still detect softmax-ness by walking back
    (ADVICE r2 medium: silently wrong loss otherwise)."""
    from flexflow_tpu.search.substitution import (
        create_partition_softmax_combine,
        propagate_parallel_state,
    )
    from flexflow_tpu.executor import _terminal_compute_op

    config = _mk_config(["-b", "8", "--mesh", "2,1,1,1"])
    ff = FFModel(config)
    x = ff.create_tensor((8, 32), name="lm_in")
    t = ff.dense(x, 8, name="lm_fc")
    ff.softmax(t, name="lm_sm")
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    sm = next(n for n in ff.graph.topo_order()
              if n.op_type == OT.OP_SOFTMAX)
    sm._is_logits = True
    xfer = create_partition_softmax_combine(2)
    matches = xfer.find_matches(ff.graph)
    assert len(matches) == 1
    ng = xfer.apply(ff.graph, matches[0])
    marked = [n for n in ng.topo_order()
              if getattr(n, "_is_logits", False)]
    assert len(marked) == 1
    assert marked[0].op_type == OT.OP_COMBINE  # marker moved to Combine
    # the walk-back recovers the softmax
    assert _terminal_compute_op(ng, marked[0]).op_type == OT.OP_SOFTMAX
