"""Telemetry subsystem: tracer/recorder units + instrumented-fit integration.

Covers the observability acceptance surface: span nesting and
thread-safety of the Chrome-trace tracer, the JSONL schema, and an
end-to-end `fit` with --telemetry-dir producing (a) a trace that parses as
Chrome trace-event JSON with compile/step/data-wait/checkpoint spans and
(b) step records carrying the data-wait and save-latency split plus a
p50/p95 summary.
"""

import json
import sys
import threading

import numpy as np
import pytest

from flexflow_tpu import telemetry
from flexflow_tpu.telemetry import log as fflog
from flexflow_tpu.telemetry.recorder import MetricsRecorder, read_jsonl
from flexflow_tpu.telemetry.tracer import Tracer


@pytest.fixture(autouse=True)
def _no_session_leak():
    """A session activated by one test must not instrument the next."""
    yield
    telemetry.deactivate()


def _events(tracer, ph=None):
    evs = tracer.to_dict()["traceEvents"]
    return [e for e in evs if ph is None or e.get("ph") == ph]


# ---------------------------------------------------------------- tracer

@pytest.mark.quick
def test_tracer_span_nesting():
    tr = Tracer()
    with tr.span("outer", phase="compile"):
        with tr.span("inner"):
            pass
        with tr.span("inner2"):
            pass
    xs = {e["name"]: e for e in _events(tr, "X")}
    assert set(xs) == {"outer", "inner", "inner2"}
    out, inn, inn2 = xs["outer"], xs["inner"], xs["inner2"]
    # children fall inside the parent interval (Perfetto nests on this)
    for child in (inn, inn2):
        assert child["ts"] >= out["ts"]
        assert child["ts"] + child["dur"] <= out["ts"] + out["dur"] + 1e-3
    assert inn2["ts"] >= inn["ts"] + inn["dur"] - 1e-3
    assert out["args"] == {"phase": "compile"}


@pytest.mark.quick
def test_tracer_thread_safety():
    tr = Tracer()
    n_threads, n_spans = 8, 200
    errors = []
    gate = threading.Barrier(n_threads)

    def worker(i):
        try:
            gate.wait()  # all threads emit concurrently (distinct idents)
            for k in range(n_spans):
                with tr.span(f"w{i}", k=k):
                    pass
        except Exception as e:  # pragma: no cover - the assertion target
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    xs = _events(tr, "X")
    assert len(xs) == n_threads * n_spans
    # every event carries its emitting thread, and each thread got a
    # thread_name metadata record
    tids = {e["tid"] for e in xs}
    assert len(tids) == n_threads
    metas = [e for e in _events(tr, "M") if e["name"] == "thread_name"]
    assert tids <= {e["tid"] for e in metas}
    # the dump is valid JSON
    json.loads(json.dumps(tr.to_dict()))


@pytest.mark.quick
def test_tracer_counter_instant_and_cap(tmp_path):
    tr = Tracer(max_events=8)
    tr.counter("c", {"v": 1})
    tr.instant("marker", step=3)
    for _ in range(50):
        tr.instant("spam")
    path = tr.dump(str(tmp_path / "trace.json"))
    data = json.load(open(path))
    phs = {e["ph"] for e in data["traceEvents"]}
    assert {"C", "i", "M"} <= phs
    # over-cap events were dropped and the drop was surfaced
    dropped = [e for e in data["traceEvents"]
               if e["name"] == "tracer.dropped_events"]
    assert dropped and dropped[0]["args"]["dropped"] > 0


# ---------------------------------------------------------------- recorder

@pytest.mark.quick
def test_recorder_jsonl_schema(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    rec = MetricsRecorder(path)
    rec.record("manifest", mesh_axes={"data": 8}, git_sha="abc")
    rec.record("step", step=1, step_time_s=0.5, data_wait_s=0.1,
               save_latency_s=0.0)
    rec.close()
    recs = read_jsonl(path)
    assert [r["kind"] for r in recs] == ["manifest", "step"]
    for r in recs:
        assert isinstance(r["t"], float)
    assert recs[0]["mesh_axes"] == {"data": 8}
    assert recs[1]["step_time_s"] == 0.5
    # a late record after close is dropped, not an exception (the async
    # checkpoint writer can outlive the session)
    rec.record("late", x=1)
    assert len(read_jsonl(path)) == 2


# ---------------------------------------------------------------- logger

@pytest.mark.quick
def test_logger_levels(capsys, monkeypatch):
    fflog.set_level("warning")
    fflog.info("invisible %d", 1)
    fflog.warning("visible %d", 2)
    out = capsys.readouterr()
    assert "invisible" not in out.out
    assert "visible 2" in out.err
    fflog.set_level("debug")
    fflog.debug("now shown")
    assert "now shown" in capsys.readouterr().out
    # FF_LOG_LEVEL is read when no explicit level was set
    monkeypatch.setenv("FF_LOG_LEVEL", "error")
    fflog._level = None
    fflog.warning("filtered")
    assert "filtered" not in capsys.readouterr().err
    fflog._level = None
    monkeypatch.delenv("FF_LOG_LEVEL")


@pytest.mark.quick
def test_disabled_telemetry_is_noop():
    telemetry.deactivate()
    s1 = telemetry.span("anything", a=1)
    s2 = telemetry.span("else")
    assert s1 is s2  # the shared no-op singleton: no allocation per call
    with s1:
        pass
    telemetry.instant("x")
    telemetry.counter("x", {"v": 1})
    telemetry.event("x", y=2)  # all silently dropped


# ---------------------------------------------------------------- fit e2e

def _build_mlp(tmp_path, extra_argv=()):
    sys.argv = ["test"] + list(extra_argv)
    from flexflow_tpu import (
        ActiMode, FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
    )

    config = FFConfig()
    ff = FFModel(config)
    x = ff.create_tensor((32, 64))
    t = ff.dense(x, 64, ActiMode.AC_MODE_RELU)
    t = ff.dense(t, 10)
    t = ff.softmax(t)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])
    return ff


def _train_data(n=256, in_dim=64):
    rs = np.random.RandomState(0)
    return (rs.randn(n, in_dim).astype(np.float32),
            rs.randint(0, 10, (n, 1)).astype(np.int32))


def test_fit_with_telemetry_dir_produces_artifacts(tmp_path):
    """The acceptance scenario: CPU-mesh fit with --telemetry-dir (+
    checkpointing) must yield a loadable Chrome trace with compile/step/
    data-wait/ckpt spans and a JSONL log with the step split + summary."""
    tdir = tmp_path / "telemetry"
    cdir = tmp_path / "ckpt"
    ff = _build_mlp(tmp_path, ["--telemetry-dir", str(tdir),
                               "--checkpoint-dir", str(cdir),
                               "--checkpoint-every", "4"])
    x, y = _train_data()
    ff.fit(x, y, epochs=1, batch_size=32)

    # (a) Chrome trace-event JSON loadable by Perfetto: an object with a
    # traceEvents list whose entries carry name/ph/ts
    trace = json.load(open(tdir / "trace.json"))
    evs = trace["traceEvents"]
    assert isinstance(evs, list)
    for e in evs:
        assert "name" in e and "ph" in e
        if e["ph"] == "X":
            assert e["ts"] >= 0 and e["dur"] >= 0
    names = {e["name"] for e in evs}
    for required in ("compile", "step", "data_wait", "ckpt.snapshot",
                     "ckpt.serialize", "ckpt.commit"):
        assert required in names, f"missing span {required!r} in {names}"
    step_spans = [e for e in evs if e["name"] == "step" and e["ph"] == "X"]
    assert len(step_spans) >= 1

    # (b) JSONL: manifest first, step records carry the data-wait /
    # save-latency split, final summary has percentiles + throughput
    recs = read_jsonl(tdir / "metrics.jsonl")
    assert recs[0]["kind"] == "manifest"
    assert recs[0]["mesh_axes"]["data"] == 8
    assert recs[0]["config"]["batch_size"] == 64
    compile_recs = [r for r in recs if r["kind"] == "compile"]
    assert compile_recs and compile_recs[0]["duration_s"] > 0
    steps = [r for r in recs if r["kind"] == "step"]
    assert len(steps) == 8  # 256 samples / batch 32
    for s in steps:
        assert s["data_wait_s"] >= 0
        assert s["save_latency_s"] >= 0
        assert s["step_time_s"] >= s["data_wait_s"]
        assert s["ema_step_time_s"] > 0
    # the policy saved at steps 4 and 8: those steps paid a snapshot
    saves = [r for r in recs if r["kind"] == "checkpoint"]
    assert len(saves) == 2
    for c in saves:
        assert c["bytes"] > 0
        assert c["serialize_s"] >= 0 and c["commit_s"] >= 0
    summary = [r for r in recs if r["kind"] == "summary"][-1]
    assert summary["steps"] == 8
    assert summary["p50_step_time_s"] > 0
    assert summary["p95_step_time_s"] >= summary["p50_step_time_s"]
    assert summary["examples_per_sec"] > 0

    assert ff.get_telemetry() is not None
    telemetry.deactivate()


def test_fit_without_telemetry_leaves_no_session(tmp_path):
    telemetry.deactivate()
    ff = _build_mlp(tmp_path)
    x, y = _train_data(n=64)
    ff.fit(x, y, epochs=1, batch_size=32)
    assert ff.get_telemetry() is None
    assert telemetry.active_session() is None


def test_keras_telemetry_callback(tmp_path):
    sys.argv = ["test"]
    from flexflow_tpu.keras.callbacks import Telemetry
    from flexflow_tpu.keras.layers import Dense, Input
    from flexflow_tpu.keras.models import Model

    tdir = tmp_path / "keras_tel"
    inp = Input(shape=(16,))
    out = Dense(10, activation="softmax")(Dense(32, activation="relu")(inp))
    model = Model(inputs=inp, outputs=out)
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    rs = np.random.RandomState(0)
    x = rs.randn(128, 16).astype(np.float32)
    y = rs.randint(0, 10, (128, 1)).astype(np.int32)
    model.fit(x, y, epochs=2, callbacks=[Telemetry(str(tdir))])

    recs = read_jsonl(tdir / "metrics.jsonl")
    kinds = {r["kind"] for r in recs}
    assert {"manifest", "step", "keras_epoch", "summary"} <= kinds
    keras_epochs = [r for r in recs if r["kind"] == "keras_epoch"]
    assert [r["epoch"] for r in keras_epochs] == [0, 1]
    assert all("accuracy" in r for r in keras_epochs)
    trace = json.load(open(tdir / "trace.json"))
    assert {"step", "data_wait"} <= {e["name"] for e in trace["traceEvents"]}
    assert model.ffmodel.get_telemetry() is not None
    telemetry.deactivate()


# ---------------------------------------------------------------- profiling

def test_profile_operators_json(tmp_path):
    from flexflow_tpu.profiling import (
        print_operator_profile, profile_operators, profile_operators_json,
    )

    ff = _build_mlp(tmp_path)
    rows = profile_operators(ff.graph)
    recs = profile_operators_json(ff.graph, rows=rows)
    assert recs and set(recs[0]) == {
        "name", "op_type", "forward_s", "backward_s", "total_s"}
    totals = [r["total_s"] for r in recs]
    assert totals == sorted(totals, reverse=True)
    for r in recs:
        assert abs(r["total_s"] - (r["forward_s"] + r["backward_s"])) < 1e-12

    # sorted table goes through the same rows; with a session active the
    # per-op counters land in the trace
    sess = telemetry.activate(
        telemetry.TelemetrySession(str(tmp_path / "prof")))
    import io

    buf = io.StringIO()
    print_operator_profile(ff.graph, file=buf, sort_by_total=True)
    assert "TOTAL" in buf.getvalue()
    counters = [e for e in sess.tracer.to_dict()["traceEvents"]
                if e["ph"] == "C" and e["name"].startswith("op_profile.")]
    assert counters
    telemetry.deactivate()
