"""fftrans transition-verifier tests (analysis/transition.py,
resilience/migrate.py, docs/analysis.md "Transition verification").

The acceptance surface: a transition-corruption fuzzer injects each of
the six corruption classes into a real (old plan → new plan) transition
— dropped weight mapping, dtype change, stage3→off without a gather
path, non-bijective transfer ring, over-cap transition peak, KV-pool
block-size mismatch — and asserts the verifier reports EXACTLY that
finding class; every cross-mesh / stage-toggle elastic-resume path the
suite exercises verifies with zero errors; `migrate_state` is bit-exact
vs checkpoint-restart (state AND continued trajectory); the
verify-before-apply restore gate refuses unverifiable mappings with a
PlanVerificationError naming the leaf + class (--no-verify-plan
downgrades); and the strategy-report `transition` section's predicted
seconds reproduce from the JSON alone (the ffcheck-identity treatment).
"""

import json
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.quick

DP8 = (8, 1, 1, 1)
DP4 = (4, 1, 1, 1)
DP4_TP2 = (4, 2, 1, 1)
DP2_TP2 = (2, 2, 1, 1)
DP2_PP4 = (2, 1, 4, 1)


def _mlp(batch=8, mesh=DP4, seed=0, argv=(), momentum=0.9):
    sys.argv = ["test", *argv]
    from flexflow_tpu import (
        ActiMode, FFConfig, FFModel, LossType, SGDOptimizer,
    )

    config = FFConfig()
    config.mesh_axis_sizes = mesh
    config.batch_size = batch
    config.seed = seed
    ff = FFModel(config)
    x = ff.create_tensor((batch, 16), name="x")
    t = ff.dense(x, 32, ActiMode.AC_MODE_RELU, name="fc1")
    t = ff.dense(t, 4, name="fc2")
    t = ff.softmax(t, name="sm")
    ff.compile(optimizer=SGDOptimizer(lr=0.05, momentum=momentum),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    return ff


def _data(n=16, d=16, k=4, seed=0):
    rs = np.random.RandomState(seed)
    x = {"x": rs.randn(n, d).astype(np.float32)}
    y = rs.randint(0, k, (n, 1)).astype(np.int32)
    return x, y


def _fit(ff, epochs=1, seed=0):
    x, y = _data(seed=seed)
    ff.fit(x, y, epochs=epochs, batch_size=8, shuffle=False,
           verbose=False)
    return ff


def _flat(tree):
    import jax.tree_util as jtu

    return {jtu.keystr(p): np.asarray(v)
            for p, v in jtu.tree_flatten_with_path(tree)[0]}


def _plan(old, new):
    from flexflow_tpu.analysis.transition import plan_model_transition

    return plan_model_transition(old, new)


def _verify(plan):
    from flexflow_tpu.analysis.transition import verify_transition

    return verify_transition(plan)


@pytest.fixture(scope="module")
def stage3_pair():
    """One (dp=4 stage-3 trained) → (dp=2×tp=2 replicated) pair shared
    by the fuzzer tests (mutations always act on a fresh plan build)."""
    old = _fit(_mlp(mesh=DP4, argv=["--weight-update-sharding=stage3"]))
    assert old._update_sharding.get("stage") == 3
    new = _mlp(mesh=DP2_TP2)
    return old, new


# ================================================== plan + identity


def test_clean_transition_verifies_and_prices(stage3_pair):
    old, new = stage3_pair
    plan = _plan(old, new)
    res = _verify(plan)
    assert res.ok, [str(f) for f in res.errors()]
    assert res.by_code("transition_clean")  # the clean marker is emitted
    assert res.passes_run == ["state_mapping", "transition_memory",
                              "transfer_collectives",
                              "migration_donation",
                              "transfer_uniformity"]
    assert plan.transfers and plan.predicted_s > 0
    # stage-3 at-rest shards must record their gather path
    sharded = [t for t in plan.transfers if t["update_sharded"]]
    assert sharded
    for t in sharded:
        assert any(c["kind"] == "all_gather" for c in t["collectives"]), t


def test_predicted_seconds_reproduce_from_json_alone(stage3_pair):
    """The ffcheck-identity treatment: predicted_s recomputes from the
    serialized per-transfer entries with nothing else in scope."""
    from flexflow_tpu.analysis.transition import verify_transition_total

    old, new = stage3_pair
    plan = _plan(old, new)
    section = json.loads(json.dumps(plan.to_json(analysis=_verify(plan))))
    total = verify_transition_total(section)
    want = section["predicted_s"]
    assert abs(total - want) <= 1e-9 + 1e-6 * abs(want)
    assert section["bytes_on_wire"]  # the per-axis wire accounting rides


# ========================================= the six-class corruption fuzzer


def test_fuzzer_dropped_weight_mapping(stage3_pair):
    old, new = stage3_pair
    plan = _plan(old, new)
    victim = next(t for t in plan.transfers
                  if "kernel" in t["key"] and "params" in t["key"])
    plan.transfers.remove(victim)
    plan.schedule_digest = __import__(
        "flexflow_tpu.analysis.transition",
        fromlist=["schedule_digest"]).schedule_digest(plan.transfers)
    res = _verify(plan)
    codes = {f.code for f in res.errors()}
    # the dropped mapping orphans the SAME leaf on both sides — exactly
    # the mapping-completeness classes, nothing else
    assert codes == {"dropped_state", "unmapped_state"}, codes
    assert any(victim["key"] == f.where
               for f in res.by_code("dropped_state"))


def test_fuzzer_dtype_change(stage3_pair):
    old, new = stage3_pair
    plan = _plan(old, new)
    victim = next(t for t in plan.transfers if "kernel" in t["key"])
    victim["dst_dtype"] = "bfloat16"
    res = _verify(plan)
    assert [f.code for f in res.errors()] == ["state_dtype_change"]
    assert res.errors()[0].where == victim["key"]


def test_fuzzer_stage3_without_gather_path(stage3_pair):
    """A stage-3 at-rest shard re-placed replicated with the gather
    collectives stripped from its transfer = the silent-corruption
    class that used to re-place partial shards as whole values."""
    old, new = stage3_pair
    plan = _plan(old, new)
    victim = next(t for t in plan.transfers if t["update_sharded"])
    victim["collectives"] = [c for c in victim["collectives"]
                             if c["kind"] != "all_gather"]
    from flexflow_tpu.analysis.transition import schedule_digest

    plan.schedule_digest = schedule_digest(plan.transfers)
    res = _verify(plan)
    assert [f.code for f in res.errors()] == ["missing_gather_path"]
    f = res.errors()[0]
    assert f.where == victim["key"]
    assert f.details.get("update_sharded") is True


def test_fuzzer_nonbijective_transfer_ring(stage3_pair, monkeypatch):
    from flexflow_tpu.parallel import ops as par_ops

    old, new = stage3_pair
    plan = _plan(old, new)
    good = par_ops.ring_permutation
    monkeypatch.setattr(par_ops, "ring_permutation",
                        lambda n: good(n)[:-1])
    res = _verify(plan)
    assert [f.code for f in res.errors()] == ["bad_transfer_permutation"]


def test_fuzzer_overcap_transition_peak(stage3_pair):
    old, new = stage3_pair
    plan = _plan(old, new)
    plan.hbm_cap_bytes = 64.0  # nothing fits in 64 bytes
    res = _verify(plan)
    assert [f.code for f in res.errors()] == ["transition_oom"]
    d = res.errors()[0].details
    assert d["peak_bytes"] > d["cap_bytes"]


def test_same_mesh_axis_move_is_not_a_missing_gather():
    """A same-mesh axis MOVE (sharded on dim 0 → dim 1) lowers to an
    all_to_all, which unwinds the axis from its old dim — it must
    verify clean, not read as a missing gather path."""
    from flexflow_tpu.analysis.transition import (
        LeafInfo, PlanSide, build_transition_plan, verify_transition,
    )

    def side(assignment):
        s = PlanSide(axis_sizes={"data": 2}, on_device=True)
        s.leaves["['params']['l']['w']"] = LeafInfo(
            key="['params']['l']['w']", shape=(4, 4), dtype="float32",
            assignment=assignment, topo_pos=0)
        return s

    plan = build_transition_plan(side((("data",), ())),
                                 side(((), ("data",))))
    moved = plan.transfers[0]
    assert [c["kind"] for c in moved["collectives"]
            if c["kind"] != "slice"] == ["all_to_all"]
    res = verify_transition(plan)
    assert res.ok, [str(f) for f in res.errors()]


def test_fuzzer_kv_pool_block_size_mismatch():
    """Synthetic serving sides (the fuzzer injects at the plan level,
    like the ffcheck fuzzer mutates axis_assignment): same pool leaf,
    different block geometry → exactly kv_pool_mismatch."""
    from flexflow_tpu.analysis.transition import (
        LeafInfo, PlanSide, build_transition_plan, verify_transition,
    )

    def side(block_size, blocks=8):
        s = PlanSide(axis_sizes={"data": 2}, on_device=True,
                     kv_block_size=block_size)
        s.leaves["['state']['attn']['pool_k']"] = LeafInfo(
            key="['state']['attn']['pool_k']",
            shape=(blocks, block_size, 16), dtype="float32",
            assignment=((), (), ()), kv_pool=True, topo_pos=0)
        return s

    clean = build_transition_plan(side(16), side(16))
    assert verify_transition(clean).ok
    plan = build_transition_plan(side(16), side(8))
    res = verify_transition(plan)
    assert set(f.code for f in res.errors()) == {"kv_pool_mismatch"}


def test_fuzzer_schedule_divergence_and_order(stage3_pair):
    """The two schedule-integrity classes: a corrupted digest no longer
    re-derives; a swapped order departs from the topological schedule."""
    from flexflow_tpu.analysis.transition import schedule_digest

    old, new = stage3_pair
    plan = _plan(old, new)
    plan.schedule_digest = "0" * 16
    res = _verify(plan)
    assert [f.code for f in res.errors()] \
        == ["transfer_schedule_divergence"]

    plan = _plan(old, new)
    a = next(t for t in plan.transfers if "fc1" in t["key"])
    b = next(t for t in plan.transfers if "fc2" in t["key"])
    a["order"], b["order"] = b["order"], a["order"]
    plan.schedule_digest = schedule_digest(plan.transfers)
    res = _verify(plan)
    assert [f.code for f in res.errors()] \
        == ["nontopological_transfer_order"]


# ================================================= migrate_state apply


@pytest.mark.parametrize("old_args,new_mesh,new_args", [
    (("--weight-update-sharding=stage3",), DP2_TP2, ()),
    ((), DP4_TP2, ("--weight-update-sharding=stage2",)),
], ids=["stage3_dp4->off_dp2tp2", "off_dp4->stage2_dp4tp2"])
def test_migrate_bit_exact_vs_checkpoint_restart(tmp_path, old_args,
                                                 new_mesh, new_args):
    """The acceptance property: in-process migration lands the SAME
    bits as a checkpoint-restart of the same state, and the continued
    trajectory stays bit-exact — across mesh factorization AND ZeRO
    stage toggles, with Adam-free SGD-momentum slots in play."""
    from flexflow_tpu.resilience import migrate_state

    old = _fit(_mlp(mesh=DP4, argv=old_args))
    old.save_checkpoint(str(tmp_path / "ck"))

    ctrl = _mlp(mesh=new_mesh, argv=new_args)
    ctrl.load_checkpoint(str(tmp_path / "ck"))
    mig = _mlp(mesh=new_mesh, argv=new_args)
    section = migrate_state(old, mig)
    assert section["analysis"]["errors"] == 0
    assert section["measured_s"] >= 0

    for name, a, b in (("params", ctrl._params, mig._params),
                       ("slots", ctrl._opt_slots, mig._opt_slots),
                       ("counters", ctrl._counters, mig._counters)):
        fa, fb = _flat(a), _flat(b)
        assert fa.keys() == fb.keys()
        for k in fa:
            assert np.array_equal(fa[k], fb[k]), f"{name}{k}"
    assert int(ctrl._step) == int(mig._step)

    # every migrated leaf carries the NEW compile's sharding
    import jax.tree_util as jtu

    for _p, leaf in jtu.tree_flatten_with_path(mig._params)[0]:
        assert leaf.sharding.mesh.shape == mig.mesh.shape

    _fit(ctrl, seed=1)
    _fit(mig, seed=1)
    fa, fb = _flat(ctrl._params), _flat(mig._params)
    for k in fa:
        assert np.array_equal(fa[k], fb[k]), k


def test_migrate_refuses_architecture_mismatch():
    """A new model whose graph differs is an unverifiable mapping: the
    gate raises PlanVerificationError NAMING the leaf and class before
    any live state moves."""
    from flexflow_tpu import (
        ActiMode, FFConfig, FFModel, LossType, SGDOptimizer,
    )
    from flexflow_tpu.analysis import PlanVerificationError
    from flexflow_tpu.resilience import migrate_state

    old = _fit(_mlp(mesh=DP4))
    sys.argv = ["test"]
    config = FFConfig()
    config.mesh_axis_sizes = DP2_TP2
    config.batch_size = 8
    other = FFModel(config)
    x = other.create_tensor((8, 16), name="x")
    t = other.dense(x, 48, ActiMode.AC_MODE_RELU, name="fc1")  # 48 != 32
    t = other.dense(t, 4, name="fc2")
    t = other.softmax(t, name="sm")
    other.compile(optimizer=SGDOptimizer(lr=0.05, momentum=0.9),
                  loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
    before = _flat(other._params)
    with pytest.raises(PlanVerificationError,
                       match="state_shape_change.*fc1"):
        migrate_state(old, other)
    after = _flat(other._params)
    for k in before:  # no live state moved
        assert np.array_equal(before[k], after[k])


def test_migrate_report_carries_transition_section(tmp_path):
    """strategy_report.json gains the `transition` section after a
    migration, with the identity reproducing and run_doctor-compatible
    analysis fields."""
    from flexflow_tpu.analysis.transition import verify_transition_total
    from flexflow_tpu.resilience import migrate_state

    old = _fit(_mlp(mesh=DP4))
    new = _mlp(mesh=DP2_TP2)
    new.enable_telemetry(str(tmp_path / "tel"))
    new.enable_diagnostics()
    migrate_state(old, new)
    with open(tmp_path / "tel" / "strategy_report.json") as f:
        report = json.load(f)
    t = report.get("transition")
    assert t is not None and t["transfers"]
    assert t["analysis"]["errors"] == 0
    total = verify_transition_total(t)
    assert abs(total - t["predicted_s"]) \
        <= 1e-9 + 1e-6 * abs(t["predicted_s"])
    assert t.get("measured_s") is not None


# ============================================ restore verify-before-apply


def _poison_leaf_dtype(root):
    """Rewrite one committed checkpoint leaf as float16 (arrays.npz +
    manifest dtype together, so load_checkpoint returns a VALID fp16
    array — the drift the gate must catch against the fp32 template)."""
    import os

    from flexflow_tpu.resilience import latest_checkpoint

    ckdir = latest_checkpoint(root)
    with open(os.path.join(ckdir, "manifest.json")) as f:
        manifest = json.load(f)
    path = next(k for k in manifest["leaves"]
                if "fc1" in k and "kernel" in k)
    meta = manifest["leaves"][path]
    npz = os.path.join(ckdir, "arrays.npz")
    data = dict(np.load(npz))
    data[meta["key"]] = data[meta["key"]].astype(np.float16)
    meta["dtype"] = "float16"
    np.savez(npz, **data)
    with open(os.path.join(ckdir, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    return path


def test_restore_gate_names_leaf_and_class(tmp_path):
    """Corrupting a committed checkpoint's leaf dtype is refused with
    the finding class + leaf name BEFORE any re-placement — the shape
    crash / silent cast mid-restore it used to be."""
    from flexflow_tpu.analysis import PlanVerificationError

    ff = _fit(_mlp(mesh=DP4))
    root = str(tmp_path / "ck")
    ff.save_checkpoint(root)
    leaf = _poison_leaf_dtype(root)

    ff2 = _mlp(mesh=DP2_TP2)
    with pytest.raises(PlanVerificationError,
                       match="state_dtype_change") as ei:
        ff2.load_checkpoint(root)
    assert leaf in str(ei.value)  # names the exact leaf


def test_restore_gate_no_verify_plan_downgrades(tmp_path):
    """--no-verify-plan downgrades the gate to warnings (the historical
    silent-cast behavior, now logged + recorded)."""
    ff = _fit(_mlp(mesh=DP4))
    root = str(tmp_path / "ck")
    ff.save_checkpoint(root)
    _poison_leaf_dtype(root)

    ff2 = _mlp(mesh=DP2_TP2, argv=["--no-verify-plan"])
    ff2.load_checkpoint(root)  # restores, casting as before
    assert ff2._transition["analysis"]["errors"] >= 1
    import jax

    assert jax.numpy.asarray(ff2._params["fc1"]["kernel"]).dtype \
        == np.float32


@pytest.mark.parametrize("resume_mesh,resume_args", [
    (DP8, ()),
    (DP4_TP2, ()),
    (DP2_PP4, ()),
    (DP8, ("--weight-update-sharding=stage2",)),
    (DP4, ("--weight-update-sharding=stage3",)),
], ids=["dp8", "dp4tp2", "dp2pp4", "dp8-stage2", "dp4-stage3"])
def test_clean_sweep_existing_resume_paths(tmp_path, resume_mesh,
                                           resume_args):
    """Every cross-mesh / stage-toggle elastic-resume shape the suite
    exercises verifies with ZERO transition errors — the gate must
    never refuse a restore that was always legal."""
    ff = _fit(_mlp(mesh=DP8, batch=8))
    root = str(tmp_path / "ck")
    ff.save_checkpoint(root)
    ff2 = _mlp(mesh=resume_mesh, argv=resume_args)
    ff2.load_checkpoint(root)
    t = ff2._transition
    assert t is not None
    assert t["analysis"]["errors"] == 0, t["analysis"]
    assert t["src"]["plan_source"] == "checkpoint"
    # a resumed fit continues cleanly on the new layout
    _fit(ff2, seed=2)


def test_transition_memory_donation_accounting(stage3_pair):
    """The timeline's donation schedule: the scheduled peak is <= the
    conservative both-layouts bound, and the two-keyed gate only errors
    when even the schedule cannot fit."""
    old, new = stage3_pair
    plan = _plan(old, new)
    res = _verify(plan)
    info = res.by_code("transition_memory_timeline")
    assert info
    d = info[0].details
    assert d["peak_bytes"] <= d["conservative_bytes"]
    assert d["timeline"]
    # cap between scheduled peak and conservative bound: donation makes
    # it fit — must NOT error
    plan2 = _plan(old, new)
    plan2.hbm_cap_bytes = d["conservative_bytes"]
    res2 = _verify(plan2)
    assert res2.ok
    assert not res2.by_code("transition_oom")
