"""Warm-start subsystem tests (warmstart/, docs/performance.md).

The acceptance surface of the persistent plan/calibration/executable
caches: a second compile against a shared `--warmstart-dir` must hit the
plan cache with ZERO search evaluations and a bit-identical strategy;
any fingerprint-component change must force a re-search; corrupt cache
entries must fall back cleanly (and self-repair); `--auto-resume` must
restore the plan from the checkpoint manifest without searching; and the
`Strategy.validate` gate must reject stale plans loudly for
`--import-strategy` while warm start treats the same failure as a miss.
"""

import json
import os
import sys

import numpy as np
import pytest

SEARCH_ARGV = ["--mesh", "2,4,1,1", "--budget", "6",
               "--enable-parameter-parallel"]


def _build(argv, hidden=256, batch=32, in_dim=64):
    """A small MLP with EXPLICIT layer names: default names embed the
    process-global layer guid, so two models built in one process would
    never share a fingerprint (separate processes — the real warm-start
    scenario — get deterministic defaults)."""
    sys.argv = ["test"] + list(argv)
    from flexflow_tpu import (
        ActiMode, FFConfig, FFModel, LossType, MetricsType, SGDOptimizer,
    )

    config = FFConfig()
    config.batch_size = batch
    ff = FFModel(config)
    x = ff.create_tensor((batch, in_dim))
    t = ff.dense(x, hidden, ActiMode.AC_MODE_RELU, name="ws_fc1")
    t = ff.dense(t, hidden, ActiMode.AC_MODE_RELU, name="ws_fc2")
    t = ff.dense(t, 10, name="ws_head")
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])
    return ff


def _strategy_json(ff) -> str:
    from flexflow_tpu.parallel.strategies import Strategy

    return json.dumps(Strategy(ff._strategy or {}).to_json(),
                      sort_keys=True)


class _EvalSpy:
    """Counts UnitySearch.evaluate calls AND joint_graph_optimize entries
    — the same hook test_strategy_io.py uses for the import path, plus
    the acceptance criterion's 0-evaluations check."""

    def __enter__(self):
        import flexflow_tpu.search.joint as joint
        import flexflow_tpu.search.unity as unity

        self.evals = 0
        self.searches = 0
        self._unity = unity
        self._joint = joint
        self._orig_eval = unity.UnitySearch.evaluate
        self._orig_opt = joint.joint_graph_optimize
        spy = self

        def eval_spy(us, *a, **kw):
            spy.evals += 1
            return spy._orig_eval(us, *a, **kw)

        def opt_spy(*a, **kw):
            spy.searches += 1
            return spy._orig_opt(*a, **kw)

        unity.UnitySearch.evaluate = eval_spy
        joint.joint_graph_optimize = opt_spy
        return self

    def __exit__(self, *exc):
        self._unity.UnitySearch.evaluate = self._orig_eval
        self._joint.joint_graph_optimize = self._orig_opt
        return False


def test_warm_compile_hits_plan_cache_zero_evals(tmp_path):
    """Second compile with a shared --warmstart-dir: plan_source=cache,
    0 evaluate() calls, 0 joint_graph_optimize calls, and the strategy is
    bit-identical to the cold run's."""
    ws = str(tmp_path / "ws")
    argv = SEARCH_ARGV + ["--warmstart-dir", ws]
    ff1 = _build(argv)
    assert ff1._plan_source == "search"
    assert os.path.isdir(os.path.join(ws, "plans"))

    with _EvalSpy() as spy:
        ff2 = _build(argv)
    assert spy.searches == 0, "plan cache hit must not re-search"
    assert spy.evals == 0, "plan cache hit must cost 0 evaluations"
    assert ff2._plan_source == "cache"
    assert _strategy_json(ff2) == _strategy_json(ff1)

    # the replayed plan still trains
    rs = np.random.RandomState(0)
    y = rs.randint(0, 10, 64)
    xs = rs.randn(64, 64).astype(np.float32)
    ff2.fit(xs, y.reshape(-1, 1).astype(np.int32), epochs=1)


def test_fingerprint_invalidation_forces_research(tmp_path):
    """Any fingerprint component change → miss: hidden size (graph),
    mesh shape, and a search flag each force a fresh search."""
    ws = str(tmp_path / "ws")
    argv = SEARCH_ARGV + ["--warmstart-dir", ws]
    _build(argv)  # populate the cache

    changed = [
        dict(argv=argv, hidden=128),                       # graph changed
        dict(argv=["--mesh", "4,2,1,1"] + argv[2:]),       # mesh changed
        dict(argv=[a if a != "6" else "4" for a in argv]),  # budget changed
    ]
    for kw in changed:
        with _EvalSpy() as spy:
            ff = _build(**kw)
        assert spy.searches >= 1, kw
        assert ff._plan_source == "search", kw

    # and the unchanged config still hits afterwards (misses were stored
    # under their own addresses, not over the original entry)
    with _EvalSpy() as spy:
        ff = _build(argv)
    assert spy.evals == 0 and ff._plan_source == "cache"


def test_corrupt_plan_entry_falls_back_and_repairs(tmp_path):
    """A truncated cache entry reads as a miss (warn, search fresh) and
    the entry is rewritten; a junk-JSON entry likewise."""
    import glob

    ws = str(tmp_path / "ws")
    argv = SEARCH_ARGV + ["--warmstart-dir", ws]
    _build(argv)
    (plan_file,) = glob.glob(os.path.join(ws, "plans", "*.json"))

    with open(plan_file, "w") as f:
        f.write('{"version": 1, "fingerpr')  # torn write
    with _EvalSpy() as spy:
        ff = _build(argv)
    assert ff._plan_source == "search" and spy.searches >= 1

    # the miss re-stored the entry: next compile hits again
    entry = json.load(open(plan_file))
    assert entry["version"] == 1 and "strategy" in entry
    with _EvalSpy() as spy:
        ff = _build(argv)
    assert ff._plan_source == "cache" and spy.evals == 0

    # wrong-model entry (valid JSON, stale content) also falls back
    entry["strategy"] = {"version": 1,
                         "nodes": {"not_a_node": {
                             "outputs": {"0": [["data"], []]},
                             "weights": {}}}}
    with open(plan_file, "w") as f:
        json.dump(entry, f)
    ff = _build(argv)
    assert ff._plan_source == "search"


def test_auto_resume_restores_plan_from_manifest(tmp_path):
    """The checkpoint manifest records the plan + structural fingerprint;
    --auto-resume adopts it at compile with zero searches, then fit
    restores the weights as before."""
    cd = str(tmp_path / "ckpt")
    argv = SEARCH_ARGV + ["--checkpoint-dir", cd, "--checkpoint-every", "2"]
    ff1 = _build(argv)
    rs = np.random.RandomState(0)
    y = rs.randint(0, 10, 128)
    xs = rs.randn(128, 64).astype(np.float32)
    ff1.fit(xs, y.reshape(-1, 1).astype(np.int32), epochs=1)

    from flexflow_tpu.resilience.checkpointer import latest_checkpoint

    path = latest_checkpoint(cd)
    assert path is not None
    man = json.load(open(os.path.join(path, "manifest.json")))
    plan = man["extras"]["plan"]
    assert plan["structural_fingerprint"] == ff1._plan_fingerprint
    assert plan["plan_source"] == "search"

    with _EvalSpy() as spy:
        ff2 = _build(argv + ["--auto-resume"])
    assert spy.searches == 0 and spy.evals == 0
    assert ff2._plan_source == "checkpoint"
    assert _strategy_json(ff2) == _strategy_json(ff1)
    # weights restore + training continues from the cursor
    ff2.fit(xs, y.reshape(-1, 1).astype(np.int32), epochs=1)
    assert ff2._py_step() > 0


def test_auto_resume_plan_mismatch_searches_fresh(tmp_path):
    """A config change between the checkpointed run and the resume must
    NOT adopt the stale plan (structural fingerprint mismatch)."""
    cd = str(tmp_path / "ckpt")
    argv = SEARCH_ARGV + ["--checkpoint-dir", cd, "--checkpoint-every", "2"]
    ff1 = _build(argv)
    rs = np.random.RandomState(0)
    y = rs.randint(0, 10, 64)
    xs = rs.randn(64, 64).astype(np.float32)
    ff1.fit(xs, y.reshape(-1, 1).astype(np.int32), epochs=1)

    with _EvalSpy() as spy:
        ff2 = _build(argv + ["--auto-resume"], hidden=128)  # graph changed
    assert spy.searches >= 1
    assert ff2._plan_source == "search"


def test_calibration_db_persists_measurements(tmp_path):
    """Cold compile with --calibrate N persists the measurements; the
    warm compile loads them and measures ZERO ops (all cache hits), and
    the compile.calibrate stats record the split."""
    from flexflow_tpu.search.cost_model import CostModel

    ws = str(tmp_path / "ws")
    argv = SEARCH_ARGV + ["--warmstart-dir", ws, "--calibrate", "1"]
    ff1 = _build(argv)
    db_path = os.path.join(ws, "calibration.json")
    assert os.path.exists(db_path)
    db = json.load(open(db_path))
    (dev_entries,) = db["devices"].values()
    assert len(dev_entries) >= 1
    for fwd_bwd in dev_entries.values():
        assert fwd_bwd[0] > 0 and fwd_bwd[1] > 0

    measured = []
    orig = CostModel.calibrate

    def spy(self, node, fn, args):
        measured.append(node.name)
        return orig(self, node, fn, args)

    CostModel.calibrate = spy
    try:
        ff2 = _build(argv)
    finally:
        CostModel.calibrate = orig
    assert measured == [], "warm calibration must be all cache hits"
    assert ff2._plan_source == "cache"
    stats = ff2._warmstart._cost_model.calib_stats
    assert stats["measured"] == 0
    assert stats["cache_hits"] >= 1
    assert ff1._plan_fingerprint == ff2._plan_fingerprint


def test_strategy_validate_rejects_stale_plans():
    """The shared validator: unknown nodes, unknown weights, absent mesh
    axes, rank mismatches, and indivisible dims all fail with messages
    naming the problem; the node's real placement passes."""
    from jax.sharding import PartitionSpec as P

    from flexflow_tpu.parallel.strategies import Strategy

    ff = _build(["--mesh", "2,4,1,1", "--only-data-parallel"])
    g, mesh = ff.graph, ff.mesh

    ok = Strategy()
    ok.set_output("ws_fc1", 0, (("data",), ("model",)))
    ok.set_weight("ws_fc1", "kernel", P(None, "model"))
    ok.validate(g, mesh)  # no raise

    bad = Strategy()
    bad.set_output("phantom_node", 0, (("data",), ()))
    with pytest.raises(ValueError, match="phantom_node"):
        bad.validate(g, mesh)

    bad = Strategy()
    bad.set_output("ws_fc1", 0, (("nonexistent_axis",), ()))
    with pytest.raises(ValueError, match="nonexistent_axis"):
        bad.validate(g, mesh)

    bad = Strategy()
    bad.set_weight("ws_fc1", "no_such_weight", P("model"))
    with pytest.raises(ValueError, match="no_such_weight"):
        bad.validate(g, mesh)

    bad = Strategy()
    bad.set_output("ws_fc1", 0, (("data",),))  # rank 1 vs 2
    with pytest.raises(ValueError, match="dims"):
        bad.validate(g, mesh)

    bad = Strategy()
    # head output dim 10 is not divisible by model axis size 4
    bad.set_output("ws_head", 0, ((), ("model",)))
    with pytest.raises(ValueError, match="divisible"):
        bad.validate(g, mesh)

    bad = Strategy()
    # 3-entry spec on a 2-D kernel: would surface as an opaque sharding
    # error deep in the executor without the validator
    bad.set_weight("ws_fc1", "kernel", P("model", None, None))
    with pytest.raises(ValueError, match="3 dims"):
        bad.validate(g, mesh)


def test_import_strategy_validates_loudly(tmp_path):
    """--import-strategy with a plan naming nodes from another model must
    raise a clear error instead of silently applying nothing."""
    plan = tmp_path / "stale.json"
    plan.write_text(json.dumps({
        "version": 1,
        "nodes": {"some_other_models_layer": {
            "outputs": {"0": [["data"], []]}, "weights": {}}},
    }))
    with pytest.raises(ValueError, match="some_other_models_layer"):
        _build(["--mesh", "2,4,1,1", "--import-strategy", str(plan)])


def test_time_to_first_step_in_summary(tmp_path):
    """The fit summary reports time_to_first_step_s (compile start →
    first step completion) — the cold-vs-warm restart metric."""
    from flexflow_tpu.telemetry import read_jsonl

    tdir = str(tmp_path / "tel")
    ff = _build(["--mesh", "2,4,1,1", "--only-data-parallel",
                 "--telemetry-dir", tdir])
    rs = np.random.RandomState(0)
    y = rs.randint(0, 10, 64)
    xs = rs.randn(64, 64).astype(np.float32)
    ff.fit(xs, y.reshape(-1, 1).astype(np.int32), epochs=1)
    recs = read_jsonl(os.path.join(tdir, "metrics.jsonl"))
    (summary,) = [r for r in recs if r["kind"] == "summary"]
    assert summary["time_to_first_step_s"] > 0
    compile_recs = [r for r in recs if r["kind"] == "compile"]
    assert compile_recs and compile_recs[0]["plan_source"] == "default"
    # first step completes after compile ends, so ttfs > compile time
    assert (summary["time_to_first_step_s"]
            > compile_recs[0]["duration_s"] * 0.5)


def test_warmstart_telemetry_records_hit(tmp_path):
    """metrics.jsonl carries the warmstart event (miss on the cold
    compile, hit on the warm one) and the compile record's plan_source
    flips search → cache."""
    from flexflow_tpu.telemetry import read_jsonl

    ws = str(tmp_path / "ws")

    def run(tag):
        tdir = str(tmp_path / tag)
        ff = _build(SEARCH_ARGV + ["--warmstart-dir", ws,
                                   "--telemetry-dir", tdir])
        # compile-only telemetry still flushes through the compile hook
        return ff, read_jsonl(os.path.join(tdir, "metrics.jsonl"))

    _, cold = run("cold")
    _, warm = run("warm")
    (cold_ws,) = [r for r in cold if r["kind"] == "warmstart"]
    (warm_ws,) = [r for r in warm if r["kind"] == "warmstart"]
    assert cold_ws["plan"] == "miss"
    assert warm_ws["plan"] == "hit" and warm_ws["source"] == "cache"
    (cold_c,) = [r for r in cold if r["kind"] == "compile"]
    (warm_c,) = [r for r in warm if r["kind"] == "compile"]
    assert cold_c["plan_source"] == "search"
    assert warm_c["plan_source"] == "cache"


def test_warm_strategy_report_describes_adopted_plan(tmp_path):
    """With --diagnostics, the warm compile's strategy report must
    attribute the ADOPTED plan (mode=replayed, same per-op configs and
    predicted makespan as the cold run's searched report) — NOT the
    data-parallel fallback, which would arm the drift monitor with the
    wrong prediction and fire false advisories on every warm restart."""
    from flexflow_tpu import telemetry

    ws = str(tmp_path / "ws")

    def run(tag):
        tdir = str(tmp_path / tag)
        # --calibrate: the warm report must price the replayed plan with
        # the persisted measurements, not the bare roofline — the parity
        # assert below fails otherwise
        ff = _build(SEARCH_ARGV + ["--warmstart-dir", ws,
                                   "--telemetry-dir", tdir,
                                   "--diagnostics", "--calibrate", "1"])
        telemetry.deactivate()
        return ff, json.load(
            open(os.path.join(tdir, "strategy_report.json")))

    _, cold = run("cold")
    warm_ff, warm = run("warm")
    assert cold["mode"] == "searched" and cold["plan_source"] == "search"
    assert warm["mode"] == "replayed" and warm["plan_source"] == "cache"
    cold_cfg = {o["name"]: o["config"] for o in cold["ops"]}
    warm_cfg = {o["name"]: o["config"] for o in warm["ops"]}
    assert warm_cfg == cold_cfg
    assert warm["total_predicted_s"] == pytest.approx(
        cold["total_predicted_s"], rel=1e-9)
    # the reconstructed (UnitySearch, choice) is stashed so drift
    # recalibration stays reachable on warm runs (_search_result is None)
    assert warm_ff._search_result is None
    us, choice = warm_ff._replay_search
    t, _ = us.evaluate(choice)
    assert t == pytest.approx(warm["total_predicted_s"], rel=1e-9)


def test_executable_cache_populated(tmp_path):
    """When the persistent XLA cache is available on this backend, the
    warm-start dir accumulates executable entries during compile. The
    model dims are unique to this test: jax memoizes compilation
    per-process by HLO hash, so an already-compiled model would never
    reach the persistent-cache layer again."""
    ws = str(tmp_path / "ws")
    ff = _build(["--mesh", "2,4,1,1", "--only-data-parallel",
                 "--warmstart-dir", ws], hidden=192, in_dim=48)
    if not ff._warmstart.executable_cache_on:
        pytest.skip("persistent compilation cache unsupported here")
    cache_dir = os.path.join(ws, "xla_cache")
    assert os.path.isdir(cache_dir)
    assert len(os.listdir(cache_dir)) > 0
