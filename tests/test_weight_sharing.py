"""Tied-weight tests: the builder's shared_op (reference dense/embedding
shared_op, model.h) and Keras shared-layer semantics — one parameter set,
gradients summed across uses."""

import sys

import numpy as np
import pytest


def _config(batch=16):
    sys.argv = ["test"]
    from flexflow_tpu import FFConfig

    config = FFConfig()
    config.mesh_axis_sizes = (1, 1, 1, 1)
    config.batch_size = batch
    return config


def test_shared_dense_one_param_set_summed_grads():
    from flexflow_tpu import ActiMode, FFModel, LossType, SGDOptimizer

    config = _config(batch=8)
    ff = FFModel(config)
    x = ff.create_tensor((8, 16))
    t1 = ff.dense(x, 16, ActiMode.AC_MODE_RELU, name="tied")
    out1 = t1
    # second use reads the SAME parameters
    t2 = ff.dense(out1, 16, ActiMode.AC_MODE_RELU, name="tied_again",
                  shared_op=t1)
    head = ff.dense(t2, 4, name="head")
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE)

    # only one parameter set exists
    assert "tied" in ff._params
    assert "tied_again" not in ff._params

    w0 = ff.get_weight("tied", "kernel").copy()
    assert np.array_equal(ff.get_weight("tied_again", "kernel"), w0)

    rs = np.random.RandomState(0)
    xs = rs.randn(16, 16).astype(np.float32)
    ys = rs.randn(16, 4).astype(np.float32)
    ff.fit(xs, ys, epochs=2)
    w1 = ff.get_weight("tied", "kernel")
    assert not np.array_equal(w1, w0), "tied weights must train"
    # both names resolve to the same updated array
    assert np.array_equal(ff.get_weight("tied_again", "kernel"), w1)


def test_shared_grads_match_manual_tied_model():
    """Numerics: a two-use tied dense must produce the same loss trajectory
    as the same function expressed in raw jax with one weight used twice."""
    import jax
    import jax.numpy as jnp

    from flexflow_tpu import ActiMode, FFModel, LossType, SGDOptimizer

    config = _config(batch=4)
    ff = FFModel(config)
    x = ff.create_tensor((4, 8))
    t1 = ff.dense(x, 8, use_bias=False, name="w")
    t2 = ff.dense(t1, 8, use_bias=False, name="w2", shared_op=t1)
    ff.compile(optimizer=SGDOptimizer(lr=0.1),
               loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE)

    rs = np.random.RandomState(0)
    xs = rs.randn(4, 8).astype(np.float32)
    ys = rs.randn(4, 8).astype(np.float32)
    w0 = ff.get_weight("w", "kernel").copy()

    # reference implementation in raw jax: y = (x @ W) @ W, SGD(0.1)
    def loss_fn(w):
        y = (jnp.asarray(xs) @ w) @ w
        return jnp.mean(jnp.sum((y - jnp.asarray(ys)) ** 2, axis=1))

    w_ref = jnp.asarray(w0)
    for _ in range(3):
        g = jax.grad(loss_fn)(w_ref)
        w_ref = w_ref - 0.1 * g

    ff.fit(xs, ys, epochs=3, shuffle=False)
    np.testing.assert_allclose(ff.get_weight("w", "kernel"),
                               np.asarray(w_ref), rtol=2e-4, atol=2e-5)


def test_shared_op_type_mismatch_raises():
    from flexflow_tpu import FFModel

    config = _config()
    ff = FFModel(config)
    x = ff.create_tensor((16, 8))
    t = ff.relu(ff.dense(x, 8, name="a"), name="r")
    with pytest.raises(ValueError, match="shared_op"):
        ff.dense(t, 8, shared_op=t)  # t is the relu output


def test_shared_embedding():
    """Tied input/output embeddings (the LM weight-tying pattern)."""
    from flexflow_tpu import FFModel, LossType, SGDOptimizer
    from flexflow_tpu.fftype import DataType

    config = _config(batch=8)
    ff = FFModel(config)
    toks = ff.create_tensor((8, 4), DataType.DT_INT32, name="toks")
    e1 = ff.embedding(toks, 32, 16, name="emb")
    toks2 = ff.create_tensor((8, 4), DataType.DT_INT32, name="toks2")
    e2 = ff.embedding(toks2, 32, 16, name="emb2", shared_op=e1)
    t = ff.add(e1, e2)
    t = ff.dense(t, 8, name="head")
    ff.compile(optimizer=SGDOptimizer(lr=0.05),
               loss_type=LossType.LOSS_MEAN_SQUARED_ERROR_AVG_REDUCE)
    assert "emb2" not in ff._params
    assert np.array_equal(ff.get_weight("emb2", "kernel"),
                          ff.get_weight("emb", "kernel"))


def test_keras_shared_layer_shares_weights():
    """A Keras layer called twice references one parameter set (was a
    documented NOTE/gap: per-call parameter copies)."""
    from flexflow_tpu.keras import Dense, Input, Model

    inp = Input(shape=(12,), batch_size=8)
    shared = Dense(12, activation="relu", name="shared_fc")
    h1 = shared(inp)
    h2 = shared(h1)  # second call: same weights
    out = Dense(4, name="head")(h2)
    m = Model(inputs=inp, outputs=out)
    m.ffconfig.batch_size = 8
    ff = m.compile(optimizer="sgd", loss="mse")
    assert "shared_fc" in ff._params
    assert "shared_fc_call1" not in ff._params
    assert np.array_equal(ff.get_weight("shared_fc_call1", "kernel"),
                          ff.get_weight("shared_fc", "kernel"))
