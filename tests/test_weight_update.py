"""Weight-update sharding (ZeRO / Xu et al. 2020) tests.

The acceptance bar: the sharded update is BIT-IDENTICAL to the replicated
baseline — same reduced gradient elements feed the same element-wise
update, each replica just owns a slice — over multi-epoch trajectories
(params, Adam slots, RNG, counters), through kill→auto-resume across an
update-mode toggle and a mesh change, while Unity's update-dimension
decision (choose_update_sharding) flips to the sharded plan exactly when
the config is memory-bound and stays replicated when overlap pricing is
off and memory fits.
"""

import sys

import numpy as np
import pytest

pytestmark = pytest.mark.quick

DP4 = (4, 1, 1, 1)
DP8 = (8, 1, 1, 1)
DP2_TP2 = (2, 2, 1, 1)


def _mlp(batch=8, mesh=DP4, seed=0, argv=(), opt="adam", depth=0):
    """2-dense MLP; `depth` adds hidden layers (fc_h*) — stage 3 only
    pays off past ~3 layers (two-layers-in-flight < whole model), so
    the stage-3 memory tests use a deeper stack."""
    sys.argv = ["test", *argv]
    from flexflow_tpu import (
        ActiMode, AdamOptimizer, FFConfig, FFModel, LossType, MetricsType,
        SGDOptimizer,
    )

    config = FFConfig()
    config.mesh_axis_sizes = mesh
    config.batch_size = batch
    config.seed = seed
    ff = FFModel(config)
    x = ff.create_tensor((batch, 16), name="x")
    t = ff.dense(x, 32, ActiMode.AC_MODE_RELU, name="fc1")
    for i in range(depth):
        t = ff.dense(t, 32, ActiMode.AC_MODE_RELU, name=f"fc_h{i}")
    t = ff.dense(t, 4, name="fc2")
    t = ff.softmax(t, name="sm")
    optimizer = (AdamOptimizer(alpha=0.01) if opt == "adam"
                 else SGDOptimizer(lr=0.05, momentum=0.9))
    ff.compile(optimizer=optimizer,
               loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
               metrics=[MetricsType.METRICS_ACCURACY])
    return ff


def _data(n=64, d=16, k=4, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, d).astype(np.float32)
    y = rs.randint(0, k, (n, 1)).astype(np.int32)
    return x, y


def _full_state(ff):
    """Every trajectory-defining leaf, fetched to host."""
    import jax

    return {
        "params": jax.tree_util.tree_map(
            lambda a: np.asarray(jax.device_get(a)), ff._params),
        "slots": jax.tree_util.tree_map(
            lambda a: np.asarray(jax.device_get(a)), ff._opt_slots),
        "counters": jax.tree_util.tree_map(
            lambda a: np.asarray(jax.device_get(a)), ff._counters),
        "step": np.asarray(jax.device_get(ff._step)),
        "rng": np.asarray(jax.random.key_data(ff._rng)),
    }


def _assert_bit_equal(a, b, what=""):
    import jax

    fa, _ = jax.tree_util.tree_flatten_with_path(a)
    fb, _ = jax.tree_util.tree_flatten_with_path(b)
    assert len(fa) == len(fb)
    for (pa, la), (_, lb) in zip(fa, fb):
        assert np.array_equal(np.asarray(la), np.asarray(lb)), (
            f"{what}{jax.tree_util.keystr(pa)} differs: "
            f"max|Δ|={np.max(np.abs(np.asarray(la, np.float64) - np.asarray(lb, np.float64)))}")


# ===================================================================
# bit-exact trajectory parity
# ===================================================================

@pytest.mark.parametrize("opt", ["adam", "sgd_momentum"])
def test_sharded_update_bit_identical_trajectory(opt):
    """2 shuffled epochs under the forced-sharded update equal the
    replicated baseline bit-for-bit: params, optimizer slots (Adam m/v or
    SGD momentum), metric counters, step counter, RNG key."""
    x, y = _data(64)

    rep = _mlp(argv=["--no-weight-update-sharding"], opt=opt)
    rep.fit(x, y, epochs=2, batch_size=8, shuffle=True)

    sh = _mlp(argv=["--weight-update-sharding"], opt=opt)
    assert sh._update_sharding["enabled"] and sh._update_sharding["shards"] == 4
    assert sh.executor.update_specs, "no weight got an update sharding"
    sh.fit(x, y, epochs=2, batch_size=8, shuffle=True)

    assert not rep._update_sharding["enabled"]
    _assert_bit_equal(_full_state(rep), _full_state(sh))


def test_sharded_masters_and_slots_live_1_over_dp():
    """The at-rest layout really is ZeRO: fp32 masters and both Adam slots
    of every sharded weight are placed 1/dp along the update axis — each
    chip's addressable shard holds 1/4 of the bytes the replicated layout
    would — and the executor's decision record counts them."""
    ff = _mlp(argv=["--weight-update-sharding"])
    specs = ff.executor.update_specs
    assert ("fc1", "kernel") in specs and ("fc2", "kernel") in specs
    for (node, wname), (spec, shape) in specs.items():
        axes = [ax for entry in spec for ax in
                ((entry,) if isinstance(entry, str) else (entry or ()))]
        assert "data" in axes, (node, wname, spec)
    k = ff._params["fc1"]["kernel"]
    shard = k.addressable_shards[0].data
    assert shard.size * 4 == k.size, (shard.shape, k.shape)
    for slot_tree in ff._opt_slots.values():
        s = slot_tree["fc1"]["kernel"]
        assert s.addressable_shards[0].data.size * 4 == s.size
    upd = ff.executor.update_sharding
    assert upd["sharded_weights"] == len(specs) and upd["buckets"] >= 2


# ===================================================================
# kill → auto-resume across update modes and meshes
# ===================================================================

def test_kill_resume_toggled_update_mode_bit_exact(tmp_path):
    """Death mid-fit under the SHARDED update, auto-resume under the
    REPLICATED update on the same mesh: the final state is bit-equal to an
    uninterrupted replicated run — checkpoints hold full logical arrays,
    so the restoring compile re-places them under its own update mode."""
    from flexflow_tpu.resilience import FaultInjector, SimulatedPreemption

    x, y = _data(64)
    root = str(tmp_path / "ck")

    ref = _mlp(argv=["--no-weight-update-sharding"])
    ref.fit(x, y, epochs=2, batch_size=8, shuffle=True)

    ff1 = _mlp(argv=["--weight-update-sharding",
                     "--checkpoint-dir", root, "--checkpoint-every", "2"])
    fault = FaultInjector(kill_after_step=5)
    ff1.set_fault_hook(fault)
    with pytest.raises(SimulatedPreemption):
        ff1.fit(x, y, epochs=2, batch_size=8, shuffle=True)
    del ff1

    ff2 = _mlp(argv=["--no-weight-update-sharding",
                     "--checkpoint-dir", root, "--auto-resume"])
    ff2.fit(x, y, epochs=2, batch_size=8, shuffle=True)
    _assert_bit_equal(_full_state(ref), _full_state(ff2))


def test_kill_resume_across_dp_change_and_back(tmp_path):
    """The acceptance scenario: dp=4 sharded → (kill) → dp=2×tp=2
    replicated → (checkpoint) → back to dp=4 sharded. The trajectory
    continues across both reshard directions; the tp=2 leg changes matmul
    reduction order, so the cross-mesh comparison is the resilience
    suite's fp tolerance, not bit-equality."""
    import jax

    from flexflow_tpu.resilience import FaultInjector, SimulatedPreemption

    x, y = _data(64)
    root = str(tmp_path / "ck")

    ref = _mlp(mesh=DP4, argv=["--no-weight-update-sharding"])
    ref.fit(x, y, epochs=3, batch_size=8, shuffle=True)
    ref_state = _full_state(ref)

    # leg 1: dp=4, ZeRO-sharded update, dies at step 5 (last commit: 4)
    ff1 = _mlp(mesh=DP4, argv=["--weight-update-sharding",
                               "--checkpoint-dir", root,
                               "--checkpoint-every", "2"])
    ff1.set_fault_hook(FaultInjector(kill_after_step=5))
    with pytest.raises(SimulatedPreemption):
        ff1.fit(x, y, epochs=3, batch_size=8, shuffle=True)
    del ff1

    # leg 2: dp=2×tp=2, replicated update, finishes epoch 2 then "dies"
    # after its final save (manifest records the replicated update mode)
    ff2 = _mlp(mesh=DP2_TP2, argv=["--no-weight-update-sharding",
                                   "--checkpoint-dir", root,
                                   "--auto-resume"])
    ff2.fit(x, y, epochs=2, batch_size=8, shuffle=True)
    assert not ff2._update_sharding["enabled"]
    ff2._resilience.save(int(np.asarray(jax.device_get(ff2._step))),
                         cursor={"epoch": 2, "batch": 0}, blocking=True)
    mani = ff2._resilience.peek_latest()[1]
    assert mani["update_sharding"]["enabled"] is False
    assert mani["mesh_axes"]["model"] == 2
    del ff2

    # leg 3: back on dp=4 with the sharded update, finishes epoch 3
    ff3 = _mlp(mesh=DP4, argv=["--weight-update-sharding",
                               "--checkpoint-dir", root, "--auto-resume"])
    ff3.fit(x, y, epochs=3, batch_size=8, shuffle=True)
    assert ff3._update_sharding["enabled"]
    got = _full_state(ff3)
    assert np.array_equal(got["step"], ref_state["step"])
    for sec in ("params", "slots", "counters"):
        fa, _ = jax.tree_util.tree_flatten_with_path(ref_state[sec])
        fb, _ = jax.tree_util.tree_flatten_with_path(got[sec])
        for (pa, la), (_, lb) in zip(fa, fb):
            np.testing.assert_allclose(
                np.asarray(la), np.asarray(lb), rtol=2e-4, atol=1e-6,
                err_msg=f"{sec}{jax.tree_util.keystr(pa)} diverged across "
                        f"dp4-sharded→dp2tp2-replicated→dp4-sharded")


def test_checkpoint_manifest_records_update_sharding(tmp_path):
    """Manifests carry the saving run's update mode (shards, axes) so
    post-mortems and elastic resume can see how the writer ran."""
    import jax

    x, y = _data(32)
    root = str(tmp_path / "ck")
    ff = _mlp(argv=["--weight-update-sharding", "--checkpoint-dir", root])
    ff.fit(x, y, epochs=1, batch_size=8, shuffle=False)
    ff._resilience.save(int(np.asarray(jax.device_get(ff._step))),
                        cursor={"epoch": 1, "batch": 0}, blocking=True)
    _, extras = ff._resilience.peek_latest()
    upd = extras["update_sharding"]
    # bare --weight-update-sharding: forced on, stage priced (memory is
    # comfortable on the CI mesh, so the bare flag resolves to stage 2)
    assert upd == {"enabled": True, "stage": 2, "shards": 4,
                   "axes": ["data"]}


# ===================================================================
# the update-dimension search (choose_update_sharding) + cost model
# ===================================================================

def test_memory_pressure_flips_search_to_sharded():
    """Auto mode (no flag): with per-chip HBM capped below the replicated
    plan's footprint (-ll:fsize), Unity's update-dimension decision flips
    to the sharded update; the predicted sharded memory is genuinely
    smaller (the 1/dp masters+slots saving)."""
    ff = _mlp(argv=["-ll:fsize", "0.007"])  # ~7 KiB/chip: memory-bound
    dec = ff._update_sharding
    assert dec["enabled"] and dec["forced"] is None
    assert dec["reason"] == "memory_bound"
    p = dec["predicted"]
    assert p["sharded_mem_bytes"] < p["replicated_mem_bytes"]
    # the replicated plan is over the cap; the sharded one fits under it
    assert p["replicated_mem_bytes"] > p["hbm_cap_bytes"]
    assert p["sharded_mem_bytes"] <= p["hbm_cap_bytes"]
    # and the executor is actually running the sharded update
    assert ff.executor.update_specs


def test_replicated_wins_when_memory_fits_and_no_overlap():
    """Auto mode with overlap pricing off and memory comfortable: RS+AG
    moves the allreduce's exact ring bytes with extra hop latency and no
    channel to hide on, so the decision stays replicated."""
    ff = _mlp(argv=["--no-overlap-collectives"])
    dec = ff._update_sharding
    assert not dec["enabled"] and dec["forced"] is None
    assert dec["reason"] == "replicated_cheaper"
    assert not ff.executor.update_specs


def test_cost_model_prices_sharded_state_and_hops():
    """CostModel.op_cost under update_sharding: per-chip memory shrinks by
    the 1/shards masters+grad+slots term, update_shards/update_hops are
    populated, and the RS+AG sync moves the same ring bytes as the
    allreduce (machine-model identity all_reduce = RS + AG)."""
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.machine_model import machine_model_for_mesh
    from flexflow_tpu.search.substitution import _logical_assignment

    ff = _mlp(argv=["--no-weight-update-sharding"])
    node = next(n for n in ff.graph.topo_order()
                if n.name == "fc1" and n.weight_specs)
    cm = CostModel(machine_model_for_mesh(ff.mesh), opt_slots=2)

    def price():
        cm._cache.clear()
        return cm.op_cost(
            node, [_logical_assignment(pt) for pt in node.outputs],
            dict(node.weight_axes),
            [tuple(d.size for d in pt.shape.dims if not d.is_replica_dim)
             for pt in node.inputs],
            [_logical_assignment(pt) for pt in node.inputs])

    rep = price()
    cm.update_sharding = True
    sh = price()
    assert rep.update_shards == 1 and rep.update_hops == 0.0
    assert rep.update_sync_time == 0.0
    assert sh.update_shards == 4 and sh.update_hops > 0.0
    assert sh.update_hop_s > 0.0
    assert sh.memory < rep.memory
    # same ring bytes: the sharded RS+AG pair (update_sync_time — the
    # channel the evaluators may overlap) prices equal to the allreduce
    # it replaces, and no serial sync remains (every weight sharded here)
    assert sh.sync_time == 0.0
    assert sh.update_sync_time == pytest.approx(rep.sync_time, rel=1e-9)
    # the 1/dp saving is exactly masters+grad+slots going to 1/shards plus
    # one gathered compute copy, per trainable weight
    saved = sum(float(np.prod(ws.shape)) * 4 * ((2 + 2) * (1 - 1 / 4) - 1)
                for ws in node.weight_specs if ws.trainable)
    assert rep.memory - sh.memory == pytest.approx(saved, rel=1e-6)


# ===================================================================
# strategy report + telemetry surface
# ===================================================================

def test_strategy_report_surfaces_grad_sync_and_identity(tmp_path):
    """strategy_report.json under the sharded update: update_sharding /
    update_shards / grad_sync_s surfaced, the grad RS+AG priced on the
    overlappable channel (overlap_s covers it), and verify_report_total
    still reproduces total_predicted_s — the makespan identity extended
    to the grad-sync channel."""
    import json
    import os

    from flexflow_tpu.diagnostics.explain import verify_report_total

    tdir = str(tmp_path / "telemetry")
    x, y = _data(32)
    ff = _mlp(argv=["--weight-update-sharding", "--diagnostics",
                    "--telemetry-dir", tdir])
    ff.fit(x, y, epochs=1, batch_size=8, shuffle=False)
    ff.get_telemetry().close()

    with open(os.path.join(tdir, "strategy_report.json")) as f:
        report = json.load(f)
    assert report["update_sharding"] is True
    assert report["update_shards"] == 4
    assert report["grad_sync_s"] > 0.0
    synced = [o for o in report["ops"] if o["grad_sync_s"] > 0.0]
    assert synced, "no op carries grad_sync_s"
    for o in synced:
        # the sharded grad sync rides the overlappable channel
        assert o["overlap_s"] >= o["grad_sync_s"]
        assert o["sync_s"] == 0.0
    total = verify_report_total(report)
    pred = report["total_predicted_s"]
    assert abs(total - pred) <= 1e-9 + 1e-6 * abs(pred)


def test_weight_update_telemetry_events(tmp_path):
    """Compile emits the weight_update event (shards, buckets, bytes) and
    per-bucket grad_sync counters; the decision event records why."""
    import os

    from flexflow_tpu.telemetry import read_jsonl

    tdir = str(tmp_path / "telemetry")
    x, y = _data(32)
    ff = _mlp(argv=["--weight-update-sharding", "--telemetry-dir", tdir])
    ff.fit(x, y, epochs=1, batch_size=8, shuffle=False)
    ff.get_telemetry().close()

    recs = list(read_jsonl(os.path.join(tdir, "metrics.jsonl")))
    wu = [r for r in recs if r.get("kind") == "weight_update"]
    assert wu and wu[0]["shards"] == 4 and wu[0]["buckets"] >= 2
    assert wu[0]["bytes"] > 0
    dec = [r for r in recs if r.get("kind") == "weight_update_decision"]
    assert dec and dec[0]["enabled"] is True

    with open(os.path.join(tdir, "trace.json")) as f:
        raw = f.read()
    assert '"grad_sync"' in raw, "no grad_sync span/counter in the trace"


# ===================================================================
# the explicit ring reduce-scatter (bench ablation substrate)
# ===================================================================

@pytest.mark.parametrize("overlap", [True, False],
                         ids=["overlapped", "serial"])
def test_ring_reduce_scatter_matches_reference(overlap):
    """ring_reduce_scatter (the double-buffered ppermute schedule the
    sharded grad sync lowers to, and bench.py's microbench subject)
    computes the exact reduce-scatter: chunk c of the output is the
    cross-shard sum of every shard's local chunk c."""
    import jax

    from flexflow_tpu.machine import MeshShape, build_mesh
    from flexflow_tpu.parallel.ops import ring_reduce_scatter

    if not hasattr(jax.Array, "addressable_shards"):  # pragma: no cover
        pytest.skip("no shard introspection")
    mesh = build_mesh(MeshShape((4, 1, 1, 1)))
    n = 4
    rs = np.random.RandomState(0)
    x = rs.randn(n * n * 2, 6).astype(np.float32)

    out = np.asarray(jax.device_get(
        ring_reduce_scatter(
            jax.device_put(x), mesh=mesh, axis_name="data",
            overlap=overlap)))

    # shard i's local block, split into n chunks; output chunk c = Σ_i block_i[c]
    locals_ = x.reshape(n, x.shape[0] // n, 6)
    chunk = x.shape[0] // n // n
    expect = np.zeros((n * chunk, 6), np.float32)
    for c in range(n):
        expect[c * chunk:(c + 1) * chunk] = sum(
            locals_[i][c * chunk:(c + 1) * chunk] for i in range(n))
    np.testing.assert_allclose(out, expect, rtol=1e-6, atol=1e-6)


def test_sharded_update_pipelined_bit_identical():
    """The sharded update composes with the fused-chunk engine: pinning
    lives in _train_step_body, which IS the chunked scan body, so
    --weight-update-sharding --pipeline-steps 4 equals the eager
    replicated baseline bit-for-bit."""
    x, y = _data(64)

    rep = _mlp(argv=["--no-weight-update-sharding"])
    rep.fit(x, y, epochs=2, batch_size=8, shuffle=True)

    sh = _mlp(argv=["--weight-update-sharding", "--pipeline-steps", "4"])
    sh.fit(x, y, epochs=2, batch_size=8, shuffle=True)
    assert sh._update_sharding["enabled"] and sh.executor.update_specs
    _assert_bit_equal(_full_state(rep), _full_state(sh))


def test_inference_and_dp1_stay_replicated():
    """No grad sync → no update sharding: a dp=1 (single-chip) compile
    auto-decides replicated with reason no_grad_sync even when forced
    would be legal — and builds no stage-3 gather machinery."""
    ff = _mlp(mesh=(1, 1, 1, 1), argv=[])
    dec = ff._update_sharding
    assert not dec["enabled"] and dec["reason"] == "no_grad_sync"
    assert dec["stage"] == 0
    assert not ff.executor.update_specs
    assert not ff.executor.gather_specs
    assert not ff.executor.gather_schedule

    # inference compile on a dp mesh: no grads, no optimizer state — no
    # update sharding and no stage-3 gathers either
    sys.argv = ["test"]
    from flexflow_tpu import (
        ActiMode, FFConfig, FFModel, LossType, SGDOptimizer,
    )
    from flexflow_tpu.fftype import CompMode

    config = FFConfig()
    config.mesh_axis_sizes = DP4
    config.batch_size = 8
    inf = FFModel(config)
    x = inf.create_tensor((8, 16), name="x")
    t = inf.dense(x, 32, ActiMode.AC_MODE_RELU, name="fc1")
    inf.dense(t, 4, name="fc2")
    inf.compile(optimizer=SGDOptimizer(lr=0.0),
                loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY,
                comp_mode=CompMode.COMP_MODE_INFERENCE)
    dec = inf._update_sharding
    assert not dec["enabled"] and dec["reason"] == "inference"
    assert dec["stage"] == 0
    assert not inf.executor.update_specs
    assert not inf.executor.gather_specs


# ===================================================================
# ZeRO-3 / FSDP stage 3: params sharded at rest + just-in-time gathers
# ===================================================================

@pytest.mark.parametrize("opt", ["adam", "sgd_momentum"])
def test_stage3_bit_identical_trajectory(opt):
    """2 shuffled epochs under forced stage 3 — params sharded at rest,
    per-layer ring all-gather just-in-time, gathered copies dropped and
    re-gathered on the backward — equal the replicated baseline
    bit-for-bit: params, optimizer slots, counters, step, RNG."""
    x, y = _data(64)

    rep = _mlp(argv=["--weight-update-sharding=off"], opt=opt)
    rep.fit(x, y, epochs=2, batch_size=8, shuffle=True)
    assert not rep._update_sharding["enabled"]

    s3 = _mlp(argv=["--weight-update-sharding=stage3"], opt=opt)
    dec = s3._update_sharding
    assert dec["enabled"] and dec["stage"] == 3 and dec["shards"] == 4
    assert s3.executor.gather_specs, "no weight got a stage-3 gather"
    assert s3.executor.gather_schedule, "no prefetch schedule built"
    # the schedule is one-layer-ahead over the PCG topo order: the first
    # gather hides behind nothing, every later one behind its predecessor
    names = [n for n, _ in s3.executor.gather_schedule]
    behinds = [b for _, b in s3.executor.gather_schedule]
    assert behinds == [None] + names[:-1]
    s3.fit(x, y, epochs=2, batch_size=8, shuffle=True)

    _assert_bit_equal(_full_state(rep), _full_state(s3))


def test_stage3_serial_schedule_bit_identical():
    """--no-overlap-collectives flips the ring bodies to the serial
    hop-then-write ablation; the values are identical either way."""
    x, y = _data(64)
    rep = _mlp(argv=["--weight-update-sharding=off"])
    rep.fit(x, y, epochs=1, batch_size=8, shuffle=False)
    s3 = _mlp(argv=["--weight-update-sharding=stage3",
                    "--no-overlap-collectives"])
    assert s3._update_sharding["stage"] == 3
    s3.fit(x, y, epochs=1, batch_size=8, shuffle=False)
    _assert_bit_equal(_full_state(rep), _full_state(s3))


def test_stage3_pipelined_bit_identical():
    """Stage 3 composes with the fused-chunk engine: the gathers live in
    _train_step_body's _apply, which IS the chunked scan body, so
    --weight-update-sharding=stage3 --pipeline-steps 4 equals the eager
    replicated baseline bit-for-bit."""
    x, y = _data(64)

    rep = _mlp(argv=["--weight-update-sharding=off"])
    rep.fit(x, y, epochs=2, batch_size=8, shuffle=True)

    s3 = _mlp(argv=["--weight-update-sharding=stage3",
                    "--pipeline-steps", "4"])
    s3.fit(x, y, epochs=2, batch_size=8, shuffle=True)
    assert s3._update_sharding["stage"] == 3 and s3.executor.gather_specs
    _assert_bit_equal(_full_state(rep), _full_state(s3))


def test_stage3_params_live_1_over_shards_at_rest():
    """The at-rest layout really is ZeRO-3: measured over the process's
    LIVE arrays (jax.live_arrays — actual allocations, not specs), each
    stage-3 param stores every byte exactly once across the mesh's
    devices, where the replicated baseline stores it once PER CHIP; and
    chip 0's addressable share is 1/shards of the logical bytes."""
    import jax

    def param_bytes(ff, key):
        leaf = ff._params[key[0]][key[1]]
        live = [a for a in jax.live_arrays() if a is leaf]
        assert live, f"{key} not among live arrays"
        arr = live[0]
        total = sum(int(s.data.size) * s.data.dtype.itemsize
                    for s in arr.addressable_shards)
        dev0 = jax.devices()[0]
        on0 = sum(int(s.data.size) * s.data.dtype.itemsize
                  for s in arr.addressable_shards if s.device == dev0)
        logical = int(np.prod(arr.shape)) * arr.dtype.itemsize
        return total, on0, logical

    rep = _mlp(argv=["--weight-update-sharding=off"])
    s3 = _mlp(argv=["--weight-update-sharding=stage3"])
    assert s3.executor.update_specs
    for key in s3.executor.update_specs:
        tot_r, on0_r, logical = param_bytes(rep, key)
        tot_s, on0_s, _ = param_bytes(s3, key)
        assert tot_r == 4 * logical and on0_r == logical  # replicated ×4
        assert tot_s == logical, key  # every byte stored once
        assert on0_s * 4 == logical, key  # 1/shards per chip
    # optimizer slots shrank identically
    for slot_tree in s3._opt_slots.values():
        s = slot_tree["fc1"]["kernel"]
        assert s.addressable_shards[0].data.size * 4 == s.size


def test_stage3_kill_resume_across_stage_toggles(tmp_path):
    """Elastic resume across stage2↔stage3↔off toggles on one mesh:
    checkpoints hold full logical arrays, so each restoring compile
    re-places them under ITS OWN stage — the whole chain stays bit-equal
    to an uninterrupted replicated run."""
    import jax

    from flexflow_tpu.resilience import FaultInjector, SimulatedPreemption

    x, y = _data(64)
    root = str(tmp_path / "ck")

    ref = _mlp(argv=["--weight-update-sharding=off"])
    ref.fit(x, y, epochs=3, batch_size=8, shuffle=True)

    # leg 1: stage 3, dies at step 5 (last commit: 4)
    ff1 = _mlp(argv=["--weight-update-sharding=stage3",
                     "--checkpoint-dir", root, "--checkpoint-every", "2"])
    assert ff1._update_sharding["stage"] == 3
    ff1.set_fault_hook(FaultInjector(kill_after_step=5))
    with pytest.raises(SimulatedPreemption):
        ff1.fit(x, y, epochs=3, batch_size=8, shuffle=True)
    del ff1

    # leg 2: stage 2 resume, finishes epoch 2, saves (manifest: stage 2)
    ff2 = _mlp(argv=["--weight-update-sharding=stage2",
                     "--checkpoint-dir", root, "--auto-resume"])
    assert ff2._update_sharding["stage"] == 2
    assert not ff2.executor.gather_specs
    ff2.fit(x, y, epochs=2, batch_size=8, shuffle=True)
    ff2._resilience.save(int(np.asarray(jax.device_get(ff2._step))),
                         cursor={"epoch": 2, "batch": 0}, blocking=True)
    mani = ff2._resilience.peek_latest()[1]
    assert mani["update_sharding"]["stage"] == 2
    del ff2

    # leg 3: replicated resume for epoch 3's first half... then back to
    # stage 3 — exercised as one final leg to keep the test fast
    ff3 = _mlp(argv=["--weight-update-sharding=stage3",
                     "--checkpoint-dir", root, "--auto-resume"])
    assert ff3._update_sharding["stage"] == 3
    ff3.fit(x, y, epochs=3, batch_size=8, shuffle=True)
    _assert_bit_equal(_full_state(ref), _full_state(ff3))


def test_memory_pressure_flips_auto_decision_to_stage3():
    """Auto mode: with the per-chip cap squeezed between stage 3's
    footprint and stage 2's (stage 2 keeps one resident gathered copy
    per weight — model bytes flat in dp), the decision must escalate to
    stage 3 with reason memory_bound; with the cap relaxed above
    stage 2, it must NOT escalate. Uses a 6-hidden-layer MLP: past ~3
    layers the two-gathered-layers-in-flight transient undercuts the
    per-weight resident copies, which is exactly when stage 3 wins."""
    probe = _mlp(argv=[], depth=6)  # price once: find stage boundaries
    pred = probe._update_sharding["predicted"]
    s2, s3 = pred["stage2_mem_bytes"], pred["stage3_mem_bytes"]
    assert s3 < s2
    mid_mib = (s2 + s3) / 2 / 2**20

    ff = _mlp(argv=["-ll:fsize", f"{mid_mib:.6f}"], depth=6)
    dec = ff._update_sharding
    assert dec["forced"] is None
    assert dec["enabled"] and dec["stage"] == 3
    assert dec["reason"] == "memory_bound"
    p = dec["predicted"]
    assert p["stage2_mem_bytes"] > p["hbm_cap_bytes"]
    assert p["stage3_mem_bytes"] <= p["hbm_cap_bytes"]
    assert ff.executor.gather_specs

    above_mib = s2 * 1.5 / 2**20
    ff2 = _mlp(argv=["-ll:fsize", f"{above_mib:.6f}"], depth=6)
    assert ff2._update_sharding["stage"] != 3


def test_programmatic_stage_pin_in_auto_mode():
    """config.weight_update_stage alone (sharding left None) pins the
    stage while enablement stays auto: on a memory-bound cap that
    auto-picks stage 3, stage=2 caps the escalation (still enabled),
    stage=0 forces replicated — the documented 0/2/3 = forced
    contract. The pinned plans may legitimately trip the OOM gate (they
    really don't fit), so the probe compiles with verify off."""
    import sys as _sys

    def build(stage=None, fsize=None):
        _sys.argv = (["test"] + (["-ll:fsize", fsize] if fsize else []))
        from flexflow_tpu import (
            ActiMode, AdamOptimizer, FFConfig, FFModel, LossType,
        )

        config = FFConfig()
        config.mesh_axis_sizes = DP4
        config.batch_size = 8
        config.weight_update_stage = stage
        if stage is not None:
            config.verify_plan = False
        ff = FFModel(config)
        x = ff.create_tensor((8, 16), name="x")
        t = ff.dense(x, 32, ActiMode.AC_MODE_RELU, name="fc1")
        for i in range(6):
            t = ff.dense(t, 32, ActiMode.AC_MODE_RELU, name=f"h{i}")
        ff.dense(t, 4, name="fc2")
        ff.compile(optimizer=AdamOptimizer(alpha=0.01),
                   loss_type=LossType.LOSS_SPARSE_CATEGORICAL_CROSSENTROPY)
        return ff._update_sharding

    pred = build()["predicted"]
    mid = (f"{(pred['stage2_mem_bytes'] + pred['stage3_mem_bytes']) / 2 / 2**20:.6f}")
    auto = build(fsize=mid)
    assert auto["forced"] is None and auto["stage"] == 3
    pin2 = build(stage=2, fsize=mid)
    assert pin2["enabled"] and pin2["stage"] == 2
    pin0 = build(stage=0, fsize=mid)
    assert not pin0["enabled"] and pin0["stage"] == 0


def test_cost_model_prices_stage3_state_and_gathers():
    """CostModel.op_cost under param_gather: per-chip memory drops the
    resident gathered copy (1/shards at rest, gather_bytes carries the
    transient), the grad sync is the RS alone, and the gather pair moves
    the deferred AG twice (fwd + bwd re-gather) — so stage-2's RS+AG
    equals stage-3's RS + half the gather pair, byte for byte."""
    from flexflow_tpu.search.cost_model import CostModel
    from flexflow_tpu.search.machine_model import machine_model_for_mesh
    from flexflow_tpu.search.substitution import _logical_assignment

    ff = _mlp(argv=["--weight-update-sharding=off"])
    node = next(n for n in ff.graph.topo_order()
                if n.name == "fc1" and n.weight_specs)
    cm = CostModel(machine_model_for_mesh(ff.mesh), opt_slots=2)

    def price():
        cm._cache.clear()
        return cm.op_cost(
            node, [_logical_assignment(pt) for pt in node.outputs],
            dict(node.weight_axes),
            [tuple(d.size for d in pt.shape.dims if not d.is_replica_dim)
             for pt in node.inputs],
            [_logical_assignment(pt) for pt in node.inputs])

    cm.update_sharding = True
    s2 = price()
    cm.param_gather = True
    s3 = price()
    assert s2.param_gather_time == 0.0 and s2.gather_bytes == 0.0
    assert s3.param_gather_time > 0.0 and s3.param_gather_hop_s > 0.0
    assert s3.gather_bytes > 0.0
    assert s3.memory < s2.memory
    # the memory delta is exactly the resident gathered copies leaving
    full_wb = sum(float(np.prod(ws.shape)) * 4
                  for ws in node.weight_specs if ws.trainable)
    assert s2.memory - s3.memory == pytest.approx(full_wb, rel=1e-6)
    assert s3.gather_bytes == pytest.approx(full_wb, rel=1e-6)
    # ring-bytes identity: RS+AG == RS + (2·AG)/2
    assert s2.update_sync_time == pytest.approx(
        s3.update_sync_time + s3.param_gather_time / 2, rel=1e-9)


def test_stage3_strategy_report_and_makespan_identity(tmp_path):
    """strategy_report.json under stage 3: update_stage/param_gather_s
    surfaced, the gathers priced on the overlappable channel, and
    verify_report_total still reproduces total_predicted_s — the
    makespan identity extended to the param-gather channel."""
    import json
    import os

    from flexflow_tpu.diagnostics.explain import verify_report_total

    tdir = str(tmp_path / "telemetry")
    x, y = _data(32)
    ff = _mlp(argv=["--weight-update-sharding=stage3", "--diagnostics",
                    "--telemetry-dir", tdir])
    ff.fit(x, y, epochs=1, batch_size=8, shuffle=False)
    ff.get_telemetry().close()

    with open(os.path.join(tdir, "strategy_report.json")) as f:
        report = json.load(f)
    assert report["update_sharding"] is True
    assert report["update_stage"] == 3
    assert report["update_shards"] == 4
    assert report["param_gather_s"] > 0.0
    gathered = [o for o in report["ops"] if o["param_gather_s"] > 0.0]
    assert gathered, "no op carries param_gather_s"
    for o in gathered:
        # gather + grad RS both ride the overlappable channel
        assert o["overlap_s"] >= o["param_gather_s"] + o["grad_sync_s"]
        assert o["sync_s"] == 0.0
    total = verify_report_total(report)
    pred = report["total_predicted_s"]
    assert abs(total - pred) <= 1e-9 + 1e-6 * abs(pred)


def test_stage3_in_plan_fingerprint():
    """The chosen stage is part of the warm-start plan fingerprint: two
    configs differing only in weight_update_stage must not share a plan
    address (the second compile of the SAME config is then a 0-eval
    hit, covered by the warm-start suite)."""
    import sys

    from flexflow_tpu.warmstart.fingerprint import (
        _SEARCH_CONFIG_FIELDS, structural_fingerprint,
    )

    assert "weight_update_stage" in _SEARCH_CONFIG_FIELDS

    ff = _mlp(argv=["--weight-update-sharding=stage3"])
    mesh_axes = {k: int(v) for k, v in ff.mesh.shape.items()}
    fp3 = structural_fingerprint(ff.graph, mesh_axes, ff.config)
    ff.config.weight_update_stage = 2
    fp2 = structural_fingerprint(ff.graph, mesh_axes, ff.config)
    assert fp3 != fp2


def test_memory_liveness_verifies_stage3_accounting():
    """The ffcheck memory-liveness pass models stage 3 as 1/shards
    persistent weights + a two-layers-in-flight gather transient: its
    persistent bytes drop vs stage 2 by exactly the resident gathered
    copies, and the recorded gather peak covers at most the two largest
    adjacent layers."""
    from flexflow_tpu.analysis import memory as mem_pass

    s2 = _mlp(argv=["--weight-update-sharding=stage2"])
    s3 = _mlp(argv=["--weight-update-sharding=stage3"])
    opt_slots = s3.optimizer.num_slots

    m2 = mem_pass.analyze(s2.graph, s2.mesh, opt_slots=opt_slots,
                          update_specs=s2.executor.update_specs,
                          update_stage=2)
    m3 = mem_pass.analyze(s3.graph, s3.mesh, opt_slots=opt_slots,
                          update_specs=s3.executor.update_specs,
                          update_stage=3)
    full_wb = sum(float(np.prod(shape)) * 4
                  for _spec, shape in s3.executor.update_specs.values())
    assert m2["persistent_bytes"] - m3["persistent_bytes"] == \
        pytest.approx(full_wb, rel=1e-6)
    assert 0.0 < m3["gather_peak_bytes"] <= full_wb
    assert m2["gather_peak_bytes"] == 0.0


@pytest.mark.parametrize("overlap", [True, False],
                         ids=["overlapped", "serial"])
def test_ring_all_gather_matches_reference(overlap):
    """ring_all_gather (the double-buffered hop-before-use schedule the
    stage-3 per-layer gather runs, and bench.py's microbench subject)
    reproduces the exact concatenation of every shard's chunk, both
    schedules."""
    import jax

    from flexflow_tpu.machine import MeshShape, build_mesh
    from flexflow_tpu.parallel.ops import ring_all_gather

    if not hasattr(jax.Array, "addressable_shards"):  # pragma: no cover
        pytest.skip("no shard introspection")
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = build_mesh(MeshShape((4, 1, 1, 1)))
    rs = np.random.RandomState(0)
    x = rs.randn(16, 6).astype(np.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P("data", None)))

    out = np.asarray(jax.device_get(
        ring_all_gather(xs, mesh=mesh, axis_name="data", dim=0,
                        overlap=overlap)))
    np.testing.assert_array_equal(out, x)
    # and along a non-leading dim
    ys = jax.device_put(x.T.copy(), NamedSharding(mesh, P(None, "data")))
    out = np.asarray(jax.device_get(
        ring_all_gather(ys, mesh=mesh, axis_name="data", dim=1,
                        overlap=overlap)))
    np.testing.assert_array_equal(out, x.T)


def test_stage3_donated_gather_executable():
    """build_param_gather: one donated dispatch gathers the whole
    sharded-at-rest tree back to full logical values (callers rebind the
    donated tree — the carry pattern the donation lint enforces)."""
    import jax

    rep = _mlp(argv=["--weight-update-sharding=off"], seed=3)
    s3 = _mlp(argv=["--weight-update-sharding=stage3"], seed=3)
    assert s3.executor.gather_specs
    gather_fn = s3.executor.build_param_gather()
    tree = {k: dict(v) for k, v in s3._params.items()}
    tree = gather_fn(tree)
    for (node, wname) in s3.executor.gather_specs:
        got = np.asarray(jax.device_get(tree[node][wname]))
        want = np.asarray(jax.device_get(rep._params[node][wname]))
        np.testing.assert_array_equal(got, want, err_msg=f"{node}.{wname}")
